"""OpTest batch 4 (VERDICT r3 item 7): metrics ops, fused RNN surface,
detection stragglers. Reference anchors: operators/metrics/auc_op.cc,
precision_recall_op.cc, operators/fused/fusion_gru_op.cc /
fusion_lstm_op.cc (+ math/detail/{gru,lstm}_kernel.h),
operators/detection/generate_proposals_v2_op.cc."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test_base import check_grad


# ---- fusion_gru ----

def _np_gru(x, wx, wh, b, origin_mode, reverse=False, h0=None):
    B, T, _ = x.shape
    H = wh.shape[0]
    xp = x @ wx + (b if b is not None else 0.0)
    h = np.zeros((B, H), np.float32) if h0 is None else h0.copy()
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))
    order = range(T - 1, -1, -1) if reverse else range(T)
    outs = np.zeros((B, T, H), np.float32)
    for t in order:
        g = xp[:, t]
        ur = sig(g[:, :2 * H] + h @ wh[:, :2 * H])
        u, r = ur[:, :H], ur[:, H:]
        m = np.tanh(g[:, 2 * H:] + (r * h) @ wh[:, 2 * H:])
        h = u * h + (1 - u) * m if origin_mode else (1 - u) * h + u * m
        outs[:, t] = h
    return outs


@pytest.mark.parametrize("origin_mode", [False, True])
def test_fusion_gru_matches_reference_formula(origin_mode):
    from paddle_tpu.incubate import fusion_gru
    rng = np.random.RandomState(0)
    B, T, I, H = 2, 5, 3, 4
    x = rng.randn(B, T, I).astype(np.float32)
    wx = (rng.randn(I, 3 * H) * 0.5).astype(np.float32)
    wh = (rng.randn(H, 3 * H) * 0.5).astype(np.float32)
    b = (rng.randn(3 * H) * 0.1).astype(np.float32)
    out = fusion_gru(paddle.to_tensor(x), paddle.to_tensor(wx),
                     paddle.to_tensor(wh), paddle.to_tensor(b),
                     origin_mode=origin_mode)
    ref = _np_gru(x, wx, wh, b, origin_mode)
    np.testing.assert_allclose(np.asarray(out.data), ref, rtol=1e-5,
                               atol=1e-5)


def test_fusion_gru_reverse_and_h0():
    from paddle_tpu.incubate import fusion_gru
    rng = np.random.RandomState(1)
    B, T, I, H = 2, 4, 3, 3
    x = rng.randn(B, T, I).astype(np.float32)
    wx = (rng.randn(I, 3 * H) * 0.5).astype(np.float32)
    wh = (rng.randn(H, 3 * H) * 0.5).astype(np.float32)
    h0 = rng.randn(B, H).astype(np.float32)
    out = fusion_gru(paddle.to_tensor(x), paddle.to_tensor(wx),
                     paddle.to_tensor(wh), h0=paddle.to_tensor(h0),
                     is_reverse=True)
    ref = _np_gru(x, wx, wh, None, False, reverse=True, h0=h0)
    np.testing.assert_allclose(np.asarray(out.data), ref, rtol=1e-5,
                               atol=1e-5)


def test_fusion_gru_grad():
    from paddle_tpu.incubate import fusion_gru
    rng = np.random.RandomState(2)
    B, T, I, H = 2, 3, 2, 3
    inputs = [rng.randn(B, T, I).astype(np.float32),
              (rng.randn(I, 3 * H) * 0.4).astype(np.float32),
              (rng.randn(H, 3 * H) * 0.4).astype(np.float32),
              (rng.randn(3 * H) * 0.1).astype(np.float32)]
    check_grad(lambda x, wx, wh, b: fusion_gru(x, wx, wh, b), inputs)


# ---- fusion_lstm ----

def _np_lstm(x, wx, wh, b, peep=False, h0=None, c0=None):
    B, T, _ = x.shape
    H = wh.shape[0]
    gb, checks = (b[:4 * H], b[4 * H:]) if b is not None and \
        b.shape[-1] == 7 * H else (b, None)
    xp = x @ wx + (gb if gb is not None else 0.0)
    h = np.zeros((B, H), np.float32) if h0 is None else h0.copy()
    c = np.zeros((B, H), np.float32) if c0 is None else c0.copy()
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))
    hs = np.zeros((B, T, H), np.float32)
    cs = np.zeros((B, T, H), np.float32)
    for t in range(T):
        g = xp[:, t] + h @ wh
        gc, gi, gf, go = (g[:, :H], g[:, H:2 * H], g[:, 2 * H:3 * H],
                          g[:, 3 * H:])
        cand = np.tanh(gc)
        if peep:
            gi = gi + c * checks[:H]
            gf = gf + c * checks[H:2 * H]
        i, f = sig(gi), sig(gf)
        c = cand * i + c * f
        if peep:
            go = go + c * checks[2 * H:]
        h = sig(go) * np.tanh(c)
        hs[:, t], cs[:, t] = h, c
    return hs, cs


@pytest.mark.parametrize("peep", [False, True])
def test_fusion_lstm_matches_reference_formula(peep):
    from paddle_tpu.incubate import fusion_lstm
    rng = np.random.RandomState(3)
    B, T, I, H = 2, 5, 3, 4
    x = rng.randn(B, T, I).astype(np.float32)
    wx = (rng.randn(I, 4 * H) * 0.5).astype(np.float32)
    wh = (rng.randn(H, 4 * H) * 0.5).astype(np.float32)
    b = (rng.randn(7 * H if peep else 4 * H) * 0.1).astype(np.float32)
    hs, cs = fusion_lstm(paddle.to_tensor(x), paddle.to_tensor(wx),
                         paddle.to_tensor(wh), paddle.to_tensor(b),
                         use_peepholes=peep)
    ref_h, ref_c = _np_lstm(x, wx, wh, b, peep=peep)
    np.testing.assert_allclose(np.asarray(hs.data), ref_h, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(cs.data), ref_c, rtol=1e-5,
                               atol=1e-5)


def test_fusion_lstm_grad():
    from paddle_tpu.incubate import fusion_lstm
    rng = np.random.RandomState(4)
    B, T, I, H = 2, 3, 2, 3
    inputs = [rng.randn(B, T, I).astype(np.float32),
              (rng.randn(I, 4 * H) * 0.4).astype(np.float32),
              (rng.randn(H, 4 * H) * 0.4).astype(np.float32)]
    check_grad(lambda x, wx, wh: fusion_lstm(x, wx, wh)[0], inputs)


def test_fusion_lstm_peepholes_require_7h_bias():
    from paddle_tpu.incubate import fusion_lstm
    rng = np.random.RandomState(5)
    x = rng.randn(1, 2, 2).astype(np.float32)
    with pytest.raises(ValueError, match="7H"):
        fusion_lstm(paddle.to_tensor(x),
                    paddle.to_tensor(rng.randn(2, 8).astype(np.float32)),
                    paddle.to_tensor(rng.randn(2, 8).astype(np.float32)),
                    paddle.to_tensor(rng.randn(8).astype(np.float32)),
                    use_peepholes=True)


# ---- auc op ----

def _np_auc(scores, labels):
    """Exact pairwise AUC (ties get half credit)."""
    pos = scores[labels > 0]
    neg = scores[labels <= 0]
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    return (wins + 0.5 * ties) / (len(pos) * len(neg))


def test_auc_op_matches_exact_pairwise():
    from paddle_tpu.metric import auc
    rng = np.random.RandomState(0)
    n = 400
    scores = rng.rand(n).astype(np.float32)
    labels = rng.randint(0, 2, (n,)).astype(np.int32)
    val, sp, sn = auc(paddle.to_tensor(scores), paddle.to_tensor(labels))
    ref = _np_auc(scores, labels)
    # binned AUC vs exact: 4095 thresholds over U[0,1) scores
    np.testing.assert_allclose(float(val.item()), ref, atol=2e-3)


def test_auc_op_streaming_equals_single_batch():
    from paddle_tpu.metric import auc
    rng = np.random.RandomState(1)
    scores = rng.rand(300).astype(np.float32)
    labels = rng.randint(0, 2, (300,)).astype(np.int32)
    v_all, _, _ = auc(paddle.to_tensor(scores), paddle.to_tensor(labels))
    v1, sp, sn = auc(paddle.to_tensor(scores[:100]),
                     paddle.to_tensor(labels[:100]))
    v2, sp, sn = auc(paddle.to_tensor(scores[100:]),
                     paddle.to_tensor(labels[100:]), stat_pos=sp,
                     stat_neg=sn)
    np.testing.assert_allclose(float(v2.item()), float(v_all.item()),
                               rtol=1e-6)


def test_auc_op_two_column_input_and_degenerate():
    from paddle_tpu.metric import auc
    probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]], np.float32)
    labels = np.array([0, 1, 0], np.int32)
    val, _, _ = auc(paddle.to_tensor(probs), paddle.to_tensor(labels))
    np.testing.assert_allclose(float(val.item()), 1.0, atol=1e-6)
    # all one class -> defined as 0 (auc_op.cc guards the 0-denominator)
    v0, _, _ = auc(paddle.to_tensor(probs),
                   paddle.to_tensor(np.zeros(3, np.int32)))
    assert float(v0.item()) == 0.0


# ---- precision_recall op ----

def _np_pr(idx, lab, C, w=None):
    w = np.ones_like(idx, np.float32) if w is None else w
    tp = np.zeros(C)
    fp = np.zeros(C)
    fn = np.zeros(C)
    for i, l, wi in zip(idx, lab, w):
        if i == l:
            tp[i] += wi
        else:
            fp[i] += wi
            fn[l] += wi

    def sdiv(a, b):
        return np.where(b > 0, a / np.where(b > 0, b, 1.0), 0.0)

    p = sdiv(tp, tp + fp)
    r = sdiv(tp, tp + fn)
    f1 = sdiv(2 * p * r, p + r)
    tps, fps, fns = tp.sum(), fp.sum(), fn.sum()
    mp = sdiv(tps, tps + fps)
    mr = sdiv(tps, tps + fns)
    mf = sdiv(2 * mp * mr, mp + mr)
    return np.array([p.mean(), r.mean(), f1.mean(), mp, mr, mf])


def test_precision_recall_matches_numpy():
    from paddle_tpu.metric import precision_recall
    rng = np.random.RandomState(0)
    C, n = 5, 200
    idx = rng.randint(0, C, (n,)).astype(np.int32)
    lab = rng.randint(0, C, (n,)).astype(np.int32)
    batch, accum, states = precision_recall(paddle.to_tensor(idx),
                                            paddle.to_tensor(lab), C)
    ref = _np_pr(idx, lab, C)
    np.testing.assert_allclose(np.asarray(batch.data), ref, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(accum.data), ref, rtol=1e-5,
                               atol=1e-6)


def test_precision_recall_streaming_and_weights():
    from paddle_tpu.metric import precision_recall
    rng = np.random.RandomState(1)
    C, n = 4, 120
    idx = rng.randint(0, C, (n,)).astype(np.int32)
    lab = rng.randint(0, C, (n,)).astype(np.int32)
    w = rng.rand(n).astype(np.float32)
    _, accum_all, _ = precision_recall(paddle.to_tensor(idx),
                                       paddle.to_tensor(lab), C,
                                       weights=paddle.to_tensor(w))
    _, _, st = precision_recall(paddle.to_tensor(idx[:50]),
                                paddle.to_tensor(lab[:50]), C,
                                weights=paddle.to_tensor(w[:50]))
    _, accum2, _ = precision_recall(paddle.to_tensor(idx[50:]),
                                    paddle.to_tensor(lab[50:]), C,
                                    weights=paddle.to_tensor(w[50:]),
                                    states=st)
    np.testing.assert_allclose(np.asarray(accum2.data),
                               np.asarray(accum_all.data), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(accum_all.data),
                               _np_pr(idx, lab, C, w), rtol=1e-4,
                               atol=1e-5)


# ---- generate_proposals ----

def test_generate_proposals_decode_clip_minsize_nms():
    from paddle_tpu.vision.ops import generate_proposals
    # 1 image, 2x2 feature map, 2 anchors per cell
    H = W = 2
    A = 2
    anchors = np.zeros((H, W, A, 4), np.float32)
    for y in range(H):
        for x in range(W):
            # anchor 0: 8x8 box; anchor 1: tiny 0.05 box (min_size victim)
            anchors[y, x, 0] = [x * 8, y * 8, x * 8 + 8, y * 8 + 8]
            anchors[y, x, 1] = [x * 8, y * 8, x * 8 + 0.05, y * 8 + 0.05]
    variances = np.ones((H, W, A, 4), np.float32)
    deltas = np.zeros((1, 4 * A, H, W), np.float32)  # identity decode
    scores = np.zeros((1, A, H, W), np.float32)
    scores[0, 0] = [[0.9, 0.8], [0.7, 0.6]]   # big anchors score high
    scores[0, 1] = 0.99                        # tiny anchors score highest
    img = np.array([[14.0, 14.0]], np.float32)  # clips the 8..16 boxes

    rois, probs, num = generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(img), paddle.to_tensor(anchors),
        paddle.to_tensor(variances), pre_nms_top_n=8, post_nms_top_n=4,
        nms_thresh=0.5, min_size=1.0, return_rois_num=True)
    rois = np.asarray(rois.data)
    probs = np.asarray(probs.data)
    # tiny anchors filtered by min_size despite top scores
    assert probs.max() <= 0.9 + 1e-6
    # ordered by score desc, boxes clipped to the 14x14 image
    assert np.all(probs[:-1, 0] >= probs[1:, 0])
    assert rois.max() <= 14.0 and rois.min() >= 0.0
    np.testing.assert_allclose(rois[0], [0, 0, 8, 8], atol=1e-5)
    assert int(np.asarray(num.data)[0]) == rois.shape[0]


def test_generate_proposals_batch_and_nms_suppression():
    from paddle_tpu.vision.ops import generate_proposals
    H = W = 1
    A = 3
    anchors = np.zeros((H, W, A, 4), np.float32)
    anchors[0, 0, 0] = [0, 0, 10, 10]
    anchors[0, 0, 1] = [0.5, 0.5, 10.5, 10.5]  # IoU ~0.82 with anchor 0
    anchors[0, 0, 2] = [20, 20, 30, 30]        # disjoint
    variances = np.ones((H, W, A, 4), np.float32)
    deltas = np.zeros((2, 4 * A, H, W), np.float32)
    scores = np.zeros((2, A, H, W), np.float32)
    scores[:, 0] = 0.9
    scores[:, 1] = 0.8
    scores[:, 2] = 0.7
    img = np.full((2, 2), 40.0, np.float32)
    rois, probs, num = generate_proposals(
        paddle.to_tensor(scores), paddle.to_tensor(deltas),
        paddle.to_tensor(img), paddle.to_tensor(anchors),
        paddle.to_tensor(variances), nms_thresh=0.5, min_size=1.0,
        return_rois_num=True)
    num = np.asarray(num.data)
    # per image: the overlapping 0.8 box is suppressed -> 2 rois each
    np.testing.assert_array_equal(num, [2, 2])
    assert np.asarray(rois.data).shape == (4, 4)


# ---- matrix_nms edge modes (VERDICT r3 item 7 stragglers) ----

def _mn_boxes():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 0] = [0.9, 0.8, 0.7]   # class 0
    scores[0, 1] = [0.6, 0.5, 0.4]   # class 1
    return boxes, scores


def test_matrix_nms_background_label_minus_one_keeps_class0():
    from paddle_tpu.vision.ops import matrix_nms
    boxes, scores = _mn_boxes()
    out_bg0, _ = matrix_nms(paddle.to_tensor(boxes),
                            paddle.to_tensor(scores), score_threshold=0.1,
                            background_label=0)
    out_all, _ = matrix_nms(paddle.to_tensor(boxes),
                            paddle.to_tensor(scores), score_threshold=0.1,
                            background_label=-1)
    cls_bg0 = set(np.asarray(out_bg0.data)[:, 0].astype(int))
    cls_all = set(np.asarray(out_all.data)[:, 0].astype(int))
    assert cls_bg0 == {1}
    assert cls_all == {0, 1}


def test_matrix_nms_return_index_maps_to_input_boxes():
    from paddle_tpu.vision.ops import matrix_nms
    boxes, scores = _mn_boxes()
    out, idx, num = matrix_nms(paddle.to_tensor(boxes),
                               paddle.to_tensor(scores),
                               score_threshold=0.1, background_label=-1,
                               return_index=True)
    out = np.asarray(out.data)
    idx = np.asarray(idx.data)
    M = boxes.shape[1]
    for row, i in zip(out, idx):
        np.testing.assert_allclose(row[2:], boxes[0, int(i) % M],
                                   atol=1e-6)


def test_matrix_nms_normalized_false_pixel_coords():
    """normalized=False uses the +1 pixel convention in the IoU — two
    touching 1-pixel boxes overlap differently, so decays must differ."""
    from paddle_tpu.vision.ops import matrix_nms
    boxes = np.array([[[0, 0, 4, 4], [1, 1, 5, 5]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]
    o_norm, _ = matrix_nms(paddle.to_tensor(boxes),
                           paddle.to_tensor(scores), score_threshold=0.1,
                           normalized=True)
    o_pix, _ = matrix_nms(paddle.to_tensor(boxes),
                          paddle.to_tensor(scores), score_threshold=0.1,
                          normalized=False)
    s_norm = np.sort(np.asarray(o_norm.data)[:, 1])
    s_pix = np.sort(np.asarray(o_pix.data)[:, 1])
    assert not np.allclose(s_norm, s_pix)


def test_precision_recall_fractional_denominator_f1():
    """Regression: safe_div must divide by denominators in (0,1) — micro-F1
    with P=R=0.4 is 0.4, not 0.32."""
    from paddle_tpu.metric import precision_recall
    idx = np.array([0, 1, 1, 1, 1], np.int32)
    lab = np.array([0, 1, 0, 0, 0], np.int32)
    batch, _, _ = precision_recall(paddle.to_tensor(idx),
                                   paddle.to_tensor(lab), 2)
    b = np.asarray(batch.data)
    np.testing.assert_allclose(b[3:], [0.4, 0.4, 0.4], atol=1e-6)


def test_generate_proposals_eta_adaptive_keeps_more():
    """eta < 1 decays the NMS threshold per kept box (adaptive NMS):
    with a decaying threshold fewer boxes are suppressed... the threshold
    only DROPS, so suppression can only increase; assert the documented
    direction: eta run keeps <= default run and differs when the decay
    crosses a pairwise IoU."""
    from paddle_tpu.vision.ops import nms
    # chain of boxes with pairwise IoU ~0.55 against the previous kept one
    boxes = np.array([[0, 0, 10, 10], [2.8, 0, 12.8, 10],
                      [5.6, 0, 15.6, 10], [30, 30, 40, 40]], np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
    keep_fix = np.asarray(nms(boxes, iou_threshold=0.6,
                              scores=scores).data)
    keep_eta = np.asarray(nms(boxes, iou_threshold=0.6, scores=scores,
                              eta=0.8).data)
    assert len(keep_eta) <= len(keep_fix)
    assert len(keep_eta) < len(keep_fix)  # 0.6 -> 0.48 suppresses the chain


def test_nms_pixel_offset_changes_iou_convention():
    from paddle_tpu.vision.ops import nms
    # small touching boxes: +1 convention raises IoU over the threshold
    boxes = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    k_norm = np.asarray(nms(boxes, iou_threshold=0.2, scores=scores).data)
    k_pix = np.asarray(nms(boxes, iou_threshold=0.2, scores=scores,
                           pixel_offset=True).data)
    assert len(k_norm) == 2   # IoU (0,1] convention: 1/7 < 0.2
    assert len(k_pix) == 1    # +1 convention: 4/14 > 0.2


def test_nms_eta_decays_before_later_candidates():
    """NMSFast ordering: after keeping box A the decayed threshold applies
    to candidate B immediately (reference suppresses B at 0.55 > 0.48)."""
    from paddle_tpu.vision.ops import nms
    boxes = np.array([[0, 0, 10, 10], [2.8, 0, 12.8, 10]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    keep = np.asarray(nms(boxes, iou_threshold=0.6, scores=scores,
                          eta=0.8).data)
    assert len(keep) == 1  # B tested at 0.48, not 0.6


def test_generate_proposals_min_size_clamped_to_one():
    """FilterBoxes clamps min_size to >= 1.0: sub-pixel boxes are dropped
    even when the caller passes min_size=0.1."""
    from paddle_tpu.vision.ops import generate_proposals
    anchors = np.zeros((1, 1, 2, 4), np.float32)
    anchors[0, 0, 0] = [0, 0, 8, 8]
    anchors[0, 0, 1] = [0, 0, 0.5, 0.5]  # 0.5px box: >= 0.1 but < 1.0
    rois, probs, num = generate_proposals(
        paddle.to_tensor(np.full((1, 2, 1, 1), 0.9, np.float32)),
        paddle.to_tensor(np.zeros((1, 8, 1, 1), np.float32)),
        paddle.to_tensor(np.array([[16., 16.]], np.float32)),
        paddle.to_tensor(anchors),
        paddle.to_tensor(np.ones((1, 1, 2, 4), np.float32)),
        min_size=0.1, return_rois_num=True)
    assert int(np.asarray(num.data)[0]) == 1
    np.testing.assert_allclose(np.asarray(rois.data)[0], [0, 0, 8, 8],
                               atol=1e-5)
