"""Training numerics observatory (ISSUE 13): in-step grad/update
telemetry riding the jitted step's extras carry, the culprit-named
non-finite blame probe, the loss-spike sentinel, and the shared
non-finite census helpers amp/pipeline/clip now delegate to — plus the
fault-matrix scenario proving an injected inf_input poisons exactly one
grad leaf and the `train_nonfinite` dump names it BEFORE the rollback
restores the params."""
import json
import math
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

import paddle_tpu as paddle
from paddle_tpu import nn, obs, optimizer as optim
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.obs import numerics as N
from paddle_tpu.obs.numerics import NumericsObservatory

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "flight_recorder.py")


@pytest.fixture(autouse=True)
def _fresh_ring_and_current():
    obs.flight_recorder().clear()
    yield
    N.set_current(None)
    obs.flight_recorder().clear()


# ---- shared non-finite census helpers ----

def test_nonfinite_count_and_total():
    a = jnp.array([1.0, np.nan, np.inf, -np.inf])
    assert int(N.nonfinite_count(a)) == 3
    assert int(N.nonfinite_count(jnp.ones((2, 2)))) == 0
    total = N.nonfinite_total([a, jnp.array([np.nan]), jnp.zeros(3)])
    assert int(total) == 4
    assert int(N.nonfinite_total([])) == 0


def test_all_finite_matches_per_leaf_reference():
    leaves = [jnp.ones((3, 2)), jnp.zeros(5), jnp.array([[2.0]])]
    bad = [jnp.ones(3), jnp.array([1.0, np.nan])]
    # parity pin vs the leaf-stacked formulation amp.GradScaler used
    # before the unification (jnp.all over per-leaf jnp.all(isfinite))
    ref = jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))
    assert bool(N.all_finite(leaves)) == bool(ref) is True
    assert bool(N.all_finite(bad)) is False
    assert bool(N.all_finite([])) is True


def test_gradscaler_unscale_uses_shared_census():
    """Behavior pin for the amp unification: found_inf flips on a single
    NaN element and stays clear for finite grads, through the shared
    all_finite helper."""
    from paddle_tpu.amp import GradScaler
    paddle.seed(0)
    lin = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=lin.parameters())
    scaler = GradScaler(init_loss_scaling=8.0)
    for p in lin.parameters():
        p.grad = Tensor(jnp.ones(p.shape, jnp.float32))
    scaler.unscale_(opt)
    assert scaler._found_inf is False
    ps = list(lin.parameters())
    assert float(np.asarray(ps[0].grad.data)[0, 0]) == pytest.approx(1 / 8)
    scaler2 = GradScaler(init_loss_scaling=8.0)
    bad = np.ones(ps[0].shape, np.float32)
    bad[0, 0] = np.nan
    ps[0].grad = Tensor(jnp.asarray(bad))
    scaler2.unscale_(opt)
    assert scaler2._found_inf is True


# ---- telemetry grouping + culprit formatting ----

def test_telemetry_groups_layer_granularity():
    groups = N.telemetry_groups(
        ["h.0.attn.wq.weight", "h.0.mlp.w1.weight", "h.11.attn.wq.weight",
         "embed.weight", "lm_head.weight"])
    assert set(groups) == {"h.0", "h.11", "embed", "lm_head"}
    assert groups["h.0"] == ["h.0.attn.wq.weight", "h.0.mlp.w1.weight"]


def test_telemetry_keys_order_is_deterministic():
    keys = N.telemetry_keys({"b": ["b.x"], "a": ["a.y"]})
    assert keys == [
        "grad_norm/a", "grad_norm/b", "grad_norm/_total",
        "param_norm/a", "param_norm/b", "param_norm/_total",
        "update_ratio/a", "update_ratio/b", "update_ratio/_total"]


def test_in_step_telemetry_norms_and_ratio():
    grads = {"w": jnp.full((2, 2), 3.0), "b": jnp.zeros(4)}
    old = {"w": jnp.full((2, 2), 4.0), "b": jnp.ones(4)}
    new = {"w": jnp.full((2, 2), 4.0) + 0.4, "b": jnp.ones(4)}
    out = N.in_step_telemetry(N.telemetry_groups(grads), grads, old, new)
    assert float(out["grad_norm/w"]) == pytest.approx(6.0)      # sqrt(4*9)
    assert float(out["param_norm/b"]) == pytest.approx(2.0)
    assert float(out["update_ratio/w"]) == pytest.approx(
        math.sqrt(4 * 0.4 ** 2) / 8.0)
    assert float(out["update_ratio/b"]) == pytest.approx(0.0)
    assert float(out["grad_norm/_total"]) == pytest.approx(6.0)


def test_bracket_path_and_culprit_spelling():
    assert N.bracket_path("h.3.attn.wq.weight") == \
        "params['h'][3]['attn']['wq']['weight']"
    assert N._human_count(1234567) == "1.2e6"
    assert N._human_count(128) == "128"
    assert N.format_leaf("h.3.attn.wq", "grad", 128, 1234567) == \
        "params['h'][3]['attn']['wq'].grad: 128 non-finite of 1.2e6"


# ---- the observatory: sampling cadence + spike sentinel ----

def test_should_sample_eager_and_chunked_agree():
    o = NumericsObservatory(interval=4)
    eager = [s for s in range(1, 17) if o.should_sample(s, 1)]
    assert eager == [4, 8, 12, 16]
    chunked = [s for s in range(4, 17, 4) if o.should_sample(s, 4)]
    assert chunked == [4, 8, 12, 16]
    with pytest.raises(ValueError):
        NumericsObservatory(interval=0)


def test_spike_sentinel_fires_and_storm_dumps(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    o = NumericsObservatory(spike_window=16, spike_zscore=6.0,
                            spike_min_points=4, storm_threshold=2)
    assert o.observe_loss(0, 1.0) is None          # warming up
    for s in range(1, 8):
        z = o.observe_loss(s, 1.0 + 0.01 * s)      # gentle drift: no fire
    assert z is not None and abs(z) < 6.0
    assert o.observe_loss(8, float("nan")) is None  # bad_loss path owns it
    z = o.observe_loss(9, 40.0)
    assert abs(z) >= 6.0 and o.loss_spikes == 1
    events = obs.flight_recorder().snapshot()["events"]
    spike = [e for e in events if e["kind"] == "train_loss_spike"]
    assert spike and spike[0]["step"] == 9 and spike[0]["storm"] is False
    # second spike reaches storm_threshold: warn once + dump
    o.observe_loss(10, 55.0)
    assert o.loss_spikes == 2
    dump = tmp_path / f"pdtpu_flight_{os.getpid()}.json"
    assert dump.exists()
    assert json.loads(dump.read_text())["reason"] == "loss_spike_storm"


def test_flat_window_never_fires_on_identical_losses():
    o = NumericsObservatory(spike_min_points=3, spike_zscore=6.0)
    for s in range(20):
        o.observe_loss(s, 0.5)                      # MAD == 0 window
    assert o.loss_spikes == 0
    # but a genuine jump off the flat window still registers
    assert abs(o.observe_loss(20, 1.0)) >= 6.0


# ---- culprit-named blame digestion ----

def test_observe_nonfinite_picks_worst_leaf_grad_first(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    o = NumericsObservatory()
    culprit = o.observe_nonfinite(7, {
        "loss": float("nan"),
        "sizes": {"h.3.attn.wq": 1234567, "b": 4},
        "grads": {"h.3.attn.wq": 128, "b": 4},
        "params": {"h.3.attn.wq": 128},            # tie -> grad wins
    })
    assert culprit == \
        "params['h'][3]['attn']['wq'].grad: 128 non-finite of 1.2e6"
    assert o.nonfinite_events == 1
    assert o.nonfinite_by_culprit == {
        "params['h'][3]['attn']['wq'].grad": 1}
    ev = [e for e in obs.flight_recorder().snapshot()["events"]
          if e["kind"] == "train_nonfinite"][0]
    assert ev["step"] == 7 and ev["culprit"] == culprit
    assert ev["grad_nonfinite"] == 132 and ev["grad_leaves"] == 2
    # blame always drops the black box (evidence outlives the rollback)
    assert (tmp_path / f"pdtpu_flight_{os.getpid()}.json").exists()


def test_observe_nonfinite_with_clean_leaves_says_downstream(tmp_path,
                                                             monkeypatch):
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    o = NumericsObservatory()
    culprit = o.observe_nonfinite(3, {"loss": float("inf"),
                                      "sizes": {"w": 8},
                                      "grads": {}, "params": {}})
    assert "downstream of the gradients" in culprit
    assert o.nonfinite_by_culprit == {"(none)": 1}


# ---- exposition: prom families + /debug/numerics ----

def test_render_prom_empty_until_first_record_then_families():
    o = NumericsObservatory()
    assert o.render_prom() == ""                   # scrape-identical off
    o.observe_sample(10, {"grad_norm/h.0": 1.5, "grad_norm/_total": 2.0,
                          "loss_scale": 1024.0})
    flat = obs.parse_exposition(o.render_prom())
    assert flat['pdtpu_train_numerics_grad_norm{group="h.0"}'] == 1.5
    assert flat['pdtpu_train_numerics_grad_norm{group="_total"}'] == 2.0
    assert flat["pdtpu_train_numerics_loss_scale"] == 1024.0
    assert flat["pdtpu_train_numerics_sample_step"] == 10
    assert flat["pdtpu_train_numerics_loss_spikes_total"] == 0


def test_debug_snapshot_and_http_route(tmp_path):
    from paddle_tpu.obs.prom import MetricsServer, TrainingMetrics
    import urllib.request
    N.set_current(None)
    assert N.debug_snapshot() == {"armed": False}
    o = NumericsObservatory(interval=2)            # ctor registers current
    o.observe_sample(2, {"grad_norm/_total": 1.0})
    o.observe_nonfinite(3, {"loss": float("nan"), "sizes": {"w": 4},
                            "grads": {"w": 4}, "params": {}})
    tm = TrainingMetrics(numerics=o)
    srv = MetricsServer([tm.render]).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/debug/numerics",
                                    timeout=30) as r:
            doc = json.loads(r.read())
        assert doc["armed"] is True and doc["nonfinite_events"] == 1
        assert doc["nonfinite_by_culprit"] == {"params['w'].grad": 1}
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            flat = obs.parse_exposition(r.read().decode())
        assert flat["pdtpu_train_numerics_nonfinite_events_total"] == 1
        key = ('pdtpu_train_numerics_nonfinite_by_culprit_total'
               '{culprit="params[\'w\'].grad"}')
        assert flat[key] == 1
    finally:
        srv.stop()


# ---- clip_grad_norm_ error_if_nonfinite semantics ----

def test_clip_grad_norm_error_if_nonfinite():
    from paddle_tpu.nn.clip import clip_grad_norm_
    paddle.seed(0)
    lin = nn.Linear(4, 2)
    params = list(lin.parameters())
    for p in params:
        p.grad = Tensor(jnp.ones(p.shape, jnp.float32))
    total = clip_grad_norm_(params, max_norm=1.0, error_if_nonfinite=True)
    assert math.isfinite(float(np.asarray(total.data)))  # finite: no raise
    bad = np.ones(params[0].shape, np.float32)
    bad[0, 0] = np.inf
    params[0].grad = Tensor(jnp.asarray(bad))
    with pytest.raises(RuntimeError, match="non-finite"):
        clip_grad_norm_(params, max_norm=1.0, error_if_nonfinite=True)
    # default keeps torch's silent behavior (scale by the non-finite norm)
    total = clip_grad_norm_(params, max_norm=1.0)
    assert not math.isfinite(float(np.asarray(total.data)))


# ---- armed step: extras carry, host sample, bit-identity, blame ----

def _mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _sharded_step(numerics):
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    from paddle_tpu.parallel import ShardedTrainStep
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(learning_rate=1e-2, parameters=model.parameters())
    mesh = _mesh()
    s = DistributedStrategy()
    s.numerics = numerics
    plan = StrategyCompiler().compile(s, opt, mesh)
    if numerics:
        assert plan.numerics is True and "numerics" in plan.applied
    step = ShardedTrainStep(
        model, opt, mesh,
        loss_fn=lambda o, y: nn.functional.mse_loss(o, y), plan=plan)
    return step, mesh


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(4, 8).astype(np.float32),
            rng.randn(4, 4).astype(np.float32))


def test_armed_step_telemetry_matches_host_recompute():
    step, _ = _sharded_step(numerics=True)
    before = {k: np.asarray(v) for k, v in step._params.items()}
    x, y = _batch()
    step(x, y)
    sample = step.numerics_host_sample()
    after = {k: np.asarray(v) for k, v in step._params.items()}
    pn = math.sqrt(sum(float((a.astype(np.float64) ** 2).sum())
                       for a in after.values()))
    assert sample["param_norm/_total"] == pytest.approx(pn, rel=1e-4)
    dn = math.sqrt(sum(float(((after[k] - before[k]).astype(
        np.float64) ** 2).sum()) for k in after))
    wn = math.sqrt(sum(float((b.astype(np.float64) ** 2).sum())
                       for b in before.values()))
    assert sample["update_ratio/_total"] == pytest.approx(dn / wn, rel=1e-3)
    assert sample["grad_norm/_total"] > 0.0
    assert set(sample) == set(N.telemetry_keys(
        N.telemetry_groups(step._params.keys())))


def test_unarmed_step_is_bit_identical_and_predicate_free():
    armed, _ = _sharded_step(numerics=True)
    plain, _ = _sharded_step(numerics=False)
    assert plain._extras.get("numerics") is None
    assert plain.numerics_host_sample() is None
    x, y = _batch()
    for _ in range(3):
        la = armed(x, y)
        lp = plain(x, y)
        # arming must not perturb the training computation by one bit
        assert np.asarray(la.data).tobytes() == np.asarray(lp.data).tobytes()
    for k in plain._params:
        assert np.asarray(plain._params[k]).tobytes() == \
            np.asarray(armed._params[k]).tobytes()


def test_nonfinite_blame_names_poisoned_leaf():
    step, _ = _sharded_step(numerics=True)
    x, y = _batch()
    step(x, y)                                      # healthy step first
    xbad = np.full_like(x, np.inf)
    report = step.nonfinite_blame(1, xbad, y)
    assert not math.isfinite(report["loss"])
    assert report["grads"]["weight"] == 32           # every element of w
    assert report["sizes"]["weight"] == 32
    assert report["probe_seconds"] > 0.0
    # healthy batch on healthy params: census comes back empty
    clean = step.nonfinite_blame(2, x, y)
    assert clean["grads"] == {} and clean["params"] == {}
    assert math.isfinite(clean["loss"])


def test_scan_step_carries_numerics_extras():
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    from paddle_tpu.parallel import ScanTrainStep, stack_batches
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(learning_rate=1e-2, parameters=model.parameters())
    mesh = _mesh()
    s = DistributedStrategy()
    s.numerics = True
    plan = StrategyCompiler().compile(s, opt, mesh)
    step = ScanTrainStep(model, opt, mesh, scan_steps=2,
                         loss_fn=lambda o, y: nn.functional.mse_loss(o, y),
                         plan=plan)
    chunk = stack_batches([_batch(0), _batch(1)])
    losses = step(*chunk)
    assert np.asarray(losses.data).shape == (2,)
    sample = step.numerics_host_sample()
    assert sample is not None and sample["grad_norm/_total"] > 0.0


# ---- corrupt_batch fault clauses ----

def test_corrupt_batch_poisons_named_element_once():
    from paddle_tpu.utils.fault_injection import FaultPlan
    plan = FaultPlan.from_spec("inf_input@3:1")
    x, y = np.ones((4, 8), np.float32), np.ones((4, 4), np.float32)
    bx, by = plan.corrupt_batch(2, (x, y))
    assert np.isfinite(by).all()                    # wrong step: untouched
    bx, by = plan.corrupt_batch(3, (x, y))
    assert np.isfinite(bx).all()
    assert np.isinf(by).all()                       # element 1 poisoned
    assert plan.log == ["inf_input@3:1"]
    bx, by = plan.corrupt_batch(3, (x, y))
    assert np.isfinite(by).all()                    # fires exactly once


def test_corrupt_batch_chunk_row_and_int_promotion():
    from paddle_tpu.utils.fault_injection import FaultPlan
    plan = FaultPlan.from_spec("nan_input@5")
    ids = np.ones((4, 2, 3), np.int32)              # [K, ...] chunk
    (out,) = plan.corrupt_batch(4, (ids,), k=4)
    assert out.dtype == np.float32                  # poison representable
    assert np.isnan(out[1]).all()                   # row = step 5 - 4
    assert np.isfinite(out[0]).all() and np.isfinite(out[2:]).all()
    t = Tensor(jnp.ones((2, 2)))
    plan2 = FaultPlan.from_spec("nan_input@0")
    out2 = plan2.corrupt_batch(0, t)
    assert isinstance(out2, Tensor)                 # wrapping preserved
    assert np.isnan(np.asarray(out2.data)).all()


# ---- ResilientTrainer arming ----

def test_trainer_numerics_off_is_one_predicate(tmp_path):
    from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                  ResilientTrainer)
    from paddle_tpu.utils.fault_injection import FaultPlan
    t = ResilientTrainer(
        lambda step: 1.0, str(tmp_path / "ckpt"),
        get_state=lambda: {}, set_state=lambda s: None,
        config=ResilientConfig(), fault_plan=FaultPlan(), use_orbax=False)
    assert t.numerics is None
    assert t.metrics.numerics is None
    summary = t.run(lambda i: i, num_steps=2)
    assert summary["completed_steps"] == 2


def test_trainer_feeds_sentinel_and_warns_on_debug_nans(tmp_path):
    from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                  ResilientTrainer)
    from paddle_tpu.utils.fault_injection import FaultPlan

    losses = {0: 1.0, 1: 1.01, 2: 0.99, 3: 1.0, 4: 1.02, 5: 0.98,
              6: 1.01, 7: 80.0}                     # step 7 spikes

    def make(numerics_obs):
        return ResilientTrainer(
            lambda step: losses[step], str(tmp_path / "ckpt"),
            get_state=lambda: {}, set_state=lambda s: None,
            config=ResilientConfig(), fault_plan=FaultPlan(),
            use_orbax=False, numerics=numerics_obs)

    o = NumericsObservatory(interval=2, spike_window=8, spike_zscore=6.0,
                            spike_min_points=4)
    t = make(o)
    assert t.numerics is o                          # shared instance wins
    summary = t.run(lambda i: i, num_steps=8)
    assert summary["completed_steps"] == 8
    assert o.loss_spikes == 1
    kinds = [e["kind"] for e in obs.flight_recorder().snapshot()["events"]]
    assert "train_loss_spike" in kinds
    # composing with FLAGS_check_nan_inf warns: debug_nans raises before
    # the blame probe can ever run
    from paddle_tpu.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": True})
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            make(True)
        assert any("FLAGS_check_nan_inf" in str(x.message) for x in w)
    finally:
        set_flags({"FLAGS_check_nan_inf": False})


# ---- postmortem CLI: non-finite-by-culprit table ----

def test_cli_groups_nonfinite_by_culprit(tmp_path):
    fr = obs.FlightRecorder()
    for s, leaf in ((3, "params['h'][3]['wq'].grad: 128 non-finite of "
                        "1.2e6"),
                    (9, "params['h'][3]['wq'].grad: 512 non-finite of "
                        "1.2e6"),
                    (12, "params['embed'].grad: 4 non-finite of 1000")):
        fr.record("train_nonfinite", step=s, culprit=leaf)
    fr.record("train_rollback", step=3)
    dump = fr.dump(path=str(tmp_path / "dump.json"), reason="unit")
    r = subprocess.run([sys.executable, CLI, dump, "--kind", "train_*"],
                       capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "non-finite events by culprit leaf:" in r.stdout
    lines = r.stdout.splitlines()
    table = [ln.strip() for ln in
             lines[lines.index("non-finite events by culprit leaf:") + 2:]]
    assert table[0].startswith("2  params['h'][3]['wq'].grad")
    assert table[1].startswith("1  params['embed'].grad")
    assert "train_rollback" in r.stdout             # glob caught it too


# ---- the fault-matrix scenario (tools/check_fault_matrix.py) ----

@pytest.mark.fault_matrix
def test_inf_input_blame_names_leaf_before_rollback(tmp_path, monkeypatch):
    """ISSUE 13 acceptance: an inf_input fault poisons the step-3 batch,
    the armed trainer's blame probe runs on that batch BEFORE the
    rollback restores the params, the `train_nonfinite` dump names
    exactly the poisoned weight leaf, and the postmortem CLI renders the
    non-finite-by-culprit table. The dump predates the rollback — it
    must not contain the `train_rollback` event that follows it."""
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    obs.flight_recorder().clear()
    step, mesh = _sharded_step(numerics=True)

    def _np(v):
        return np.asarray(v.data if isinstance(v, Tensor) else v)

    def get_state():
        return {"params": {k: np.asarray(v)
                           for k, v in step._params.items()},
                "opt": {k: {s: np.asarray(a) for s, a in d.items()}
                        for k, d in step._opt_state.items()}}

    def set_state(st):
        step._params = {
            k: jax.device_put(_np(v),
                              NamedSharding(mesh, step.param_specs[k]))
            for k, v in st["params"].items()}
        step._opt_state = {
            k: {s: jax.device_put(
                _np(a), NamedSharding(mesh, step.opt_state_specs[k][s]))
                for s, a in d.items()}
            for k, d in st["opt"].items()}

    from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                  ResilientTrainer)
    from paddle_tpu.utils.fault_injection import FaultPlan
    batches = [_batch(i) for i in range(6)]
    t = ResilientTrainer(
        step, str(tmp_path / "ckpt"), get_state=get_state,
        set_state=set_state,
        config=ResilientConfig(save_interval=1, nan_policy="rollback"),
        fault_plan=FaultPlan.from_spec("inf_input@3"),
        use_orbax=False, numerics=True, numerics_interval=2,
        goodput=True)
    summary = t.run(lambda i: batches[i], num_steps=6)
    assert summary["completed_steps"] == 6
    assert summary["rollbacks"] == 1
    assert any(e["kind"] == "bad_loss" and e["step"] == 3
               for e in summary["events"])

    # probe wall time books as recovery overhead, not training
    assert summary["goodput"]["phase_seconds"]["rollback_waste"] > 0.0

    # the observatory blamed exactly the poisoned leaf: inf inputs drive
    # every element of the weight grad non-finite
    snap = t.numerics.snapshot()
    assert snap["nonfinite_events"] == 1
    assert list(snap["nonfinite_by_culprit"]) == ["params['weight'].grad"]
    # ...and the in-step telemetry sampled the clean steps around it
    assert snap["samples"] >= 1
    assert snap["last_sample"]["grad_norm/_total"] > 0.0

    # the dump was cut at blame time: it names the culprit and does NOT
    # yet contain the rollback that follows
    dump_path = tmp_path / f"pdtpu_flight_{os.getpid()}.json"
    assert dump_path.exists()
    doc = json.loads(dump_path.read_text())
    assert doc["reason"] == "train_nonfinite"
    kinds = [e["kind"] for e in doc["events"]]
    assert "train_nonfinite" in kinds and "train_bad_loss" in kinds
    assert "train_rollback" not in kinds            # blame BEFORE rollback
    nfe = [e for e in doc["events"] if e["kind"] == "train_nonfinite"][0]
    assert nfe["step"] == 3
    assert nfe["culprit"].startswith(
        "params['weight'].grad: 32 non-finite of 32")
    assert nfe["probe_seconds"] > 0.0

    # postmortem CLI renders the grouped table from the same dump
    r = subprocess.run(
        [sys.executable, CLI, str(dump_path), "--kind", "train_*"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "non-finite events by culprit leaf:" in r.stdout
    assert "params['weight'].grad" in r.stdout
