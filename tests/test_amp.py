"""AMP: bf16 training with fp32 master weights + GradScaler behavior."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer


def test_master_weights_accumulate_small_updates():
    # bf16 has ~8 bits of mantissa: 1.0 + 0.001 == 1.0 in bf16. With
    # multi_precision the fp32 master accumulates 100 such updates.
    w = paddle.core.tensor.Parameter(
        np.ones(4, np.float32), name="w")
    w.data = w.data.astype(jnp.bfloat16)
    opt = optimizer.SGD(learning_rate=0.001, parameters=[w],
                        multi_precision=True)
    for _ in range(100):
        w.grad = paddle.Tensor(jnp.full((4,), -1.0, jnp.bfloat16))
        opt.step()
        opt.clear_grad()
    # master accumulated 0.1; bf16-only training would stay at 1.0
    np.testing.assert_allclose(w.numpy().astype(np.float32),
                               np.full(4, 1.1), rtol=5e-3)
    master = opt._state[id(w)]["master_weight"]
    np.testing.assert_allclose(np.asarray(master), np.full(4, 1.1),
                               rtol=1e-5)


def test_without_master_weights_bf16_stalls():
    w = paddle.core.tensor.Parameter(np.ones(4, np.float32))
    w.data = w.data.astype(jnp.bfloat16)
    opt = optimizer.SGD(learning_rate=0.001, parameters=[w])
    for _ in range(10):
        w.grad = paddle.Tensor(jnp.full((4,), -1.0, jnp.bfloat16))
        opt.step()
        opt.clear_grad()
    # updates vanish in bf16 rounding — documents WHY multi_precision exists
    np.testing.assert_allclose(w.numpy().astype(np.float32), np.ones(4))


def test_auto_cast_context():
    with amp.auto_cast(True, dtype="bfloat16"):
        assert amp.amp_state().enabled
        assert amp.amp_state().dtype == jnp.bfloat16
    assert not amp.amp_state().enabled


def test_auto_cast_O1_casts_matmul_to_bf16():
    # behavior, not flags: fp32 inputs to a white-listed op come out bf16
    x = paddle.randn([4, 8])
    w = paddle.randn([8, 4])
    with amp.auto_cast(True, dtype="bfloat16"):
        out = paddle.matmul(x, w)
    assert out.dtype == np.dtype(paddle.bfloat16)
    out_fp32 = paddle.matmul(x, w)
    assert out_fp32.dtype == np.float32


def test_auto_cast_O1_linear_and_conv():
    model = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    with amp.auto_cast(True, dtype="bfloat16"):
        y = model(x)
    assert y.dtype == np.dtype(paddle.bfloat16)
    conv = nn.Conv2D(3, 4, 3)
    img = paddle.randn([1, 3, 8, 8])
    with amp.auto_cast(True, dtype="bfloat16"):
        o = conv(img)
    assert o.dtype == np.dtype(paddle.bfloat16)


def test_auto_cast_blacklist_softmax_runs_fp32():
    x = paddle.randn([4, 8]).astype("bfloat16")
    with amp.auto_cast(True, dtype="bfloat16"):
        p = nn.functional.softmax(x)
    assert p.dtype == np.float32


def test_auto_cast_custom_lists_override_defaults():
    x = paddle.randn([4, 8])
    w = paddle.randn([8, 4])
    with amp.auto_cast(True, dtype="bfloat16",
                       custom_black_list={"matmul"}):
        out = paddle.matmul(x, w)
    assert out.dtype == np.float32


def test_auto_cast_grads_flow_through_casts():
    w = paddle.core.tensor.Parameter(np.ones((4, 4), np.float32))
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with amp.auto_cast(True, dtype="bfloat16"):
        loss = paddle.matmul(x, w).sum()
    loss.backward()
    assert w.grad is not None
    assert w.grad.dtype == np.float32  # cotangent cast back to param dtype
    np.testing.assert_allclose(w.grad.numpy(), np.full((4, 4), 2.0))


def test_auto_cast_retraces_jit_path():
    # the amp state is part of the jit cache key: same StaticFunction called
    # with and without auto_cast yields different output dtypes
    fn = paddle.jit.to_static(lambda a: paddle.matmul(a, a))
    x = paddle.randn([4, 4])
    y1 = fn(x)
    with amp.auto_cast(True, dtype="bfloat16"):
        y2 = fn(x)
    y3 = fn(x)
    assert y1.dtype == np.float32
    assert y2.dtype == np.dtype(paddle.bfloat16)
    assert y3.dtype == np.float32


def test_grad_scaler_skips_on_inf():
    w = paddle.core.tensor.Parameter(np.array([1.0], np.float32))
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = amp.GradScaler(init_loss_scaling=4.0)
    w.grad = paddle.to_tensor(np.array([np.inf], np.float32))
    scaler.step(opt)
    scaler.update()  # reference usage: step() then update()
    np.testing.assert_allclose(w.numpy(), [1.0])  # step skipped
    assert scaler._scale < 4.0 or scaler._bad_steps > 0


def test_grad_scaler_scales_and_unscales():
    w = paddle.core.tensor.Parameter(np.array([1.0], np.float32))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w])
    scaler = amp.GradScaler(init_loss_scaling=8.0)
    loss = (w * 2.0).sum()
    scaler.scale(loss).backward()
    np.testing.assert_allclose(w.grad.numpy(), [16.0])  # scaled grad
    scaler.step(opt)
    np.testing.assert_allclose(w.numpy(), [-1.0])  # unscaled update (grad 2)


def test_o2_decorate_casts_model():
    model = nn.Linear(4, 4)
    amp.decorate(model, level="O2", dtype="bfloat16")
    assert model.weight.dtype == np.dtype(paddle.bfloat16)


def test_jit_train_step_with_master_weights():
    paddle.seed(0)
    model = nn.Linear(8, 4)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=model.parameters(),
                          multi_precision=True)
    step = paddle.jit.TrainStep(
        model, lambda o, y: nn.functional.mse_loss(
            o.astype("float32"), y), opt)
    x = paddle.randn([16, 8]).astype("bfloat16")
    y = paddle.randn([16, 4])
    losses = [float(step(x, y).item()) for _ in range(15)]
    assert losses[-1] < losses[0]
    # master slots exist in the functional state
    assert any("master_weight" in slots
               for slots in step._opt_state.values())
