"""Serving economics ledger (ISSUE 11): pump phase attribution that
tiles the serving engines' wall clock, token economics over the
fixed-width unified step, per-tenant / per-SLO-class device-time cost
metering, the SLO burn-rate monitor (multi-window multi-burn), the
Prometheus label-escaping regression, and the dispatch-storm
fault-matrix scenario proving a burn-rate crossing lands in the black
box BEFORE the breaker-open it predicts.

Ledger unit tests run on an injected fake clock (exact numbers); engine
tests run the PRODUCTION pump under a SimClock — the ticking variant
auto-advances on every read, so device spans, host spans, and idle gaps
are all nonzero and the tiling reconciliation is a real proof."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.obs.serving_ledger import (SERVING_LEDGER_PHASES,
                                           ServingLedger, SLOBurnMonitor)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "flight_recorder.py")


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


def _ticking_sim_clock(tick=0.0005):
    """A SimClock whose every now() read advances time by `tick`: the
    engine stays threadless (pump-driven), but clock spans between any
    two reads are nonzero and exactly countable."""
    from paddle_tpu.serving.clock import SimClock

    class _Ticking(SimClock):
        def now(self):
            self._t += tick
            return self._t

    return _Ticking()


# ---- ServingLedger unit tests (fake clock, exact numbers) ----

def test_book_dispatch_splits_compute_and_tiles_host_frame():
    fc = FakeClock()
    led = ServingLedger(clock=fc)
    led.start()
    with led.measure("host"):
        fc.tick(0.010)
        led.book_dispatch(0.004, prefill_positions=3, decode_positions=1,
                          total_positions=32,
                          owners=[("a", "interactive", 3), ("b", "batch", 1)])
    fc.tick(0.002)
    snap = led.snapshot()
    ph = snap["phase_seconds"]
    assert set(ph) == set(SERVING_LEDGER_PHASES)
    # device span split 3:1 between the compute phases, charged OUT of
    # the enclosing host frame; the residual is idle
    assert ph["prefill_compute"] == pytest.approx(0.003)
    assert ph["decode_compute"] == pytest.approx(0.001)
    assert ph["host"] == pytest.approx(0.006)
    assert ph["idle"] == pytest.approx(0.002)
    assert snap["wall_seconds"] == pytest.approx(0.012)
    assert sum(ph.values()) == pytest.approx(snap["wall_seconds"])
    # owners carry the SAME seconds, apportioned by position weight
    assert snap["tenants"]["a"]["device_seconds"] == pytest.approx(0.003)
    assert snap["tenants"]["b"]["device_seconds"] == pytest.approx(0.001)
    assert snap["classes"]["interactive"]["tokens"] == 3
    assert snap["token_efficiency"] == pytest.approx(4 / 32)
    assert snap["prefill_tokens"] == 3 and snap["decode_tokens"] == 1


def test_zero_useful_dispatch_books_host_and_mfu_registration():
    from paddle_tpu.obs.flops import decode_mfu
    fc = FakeClock()
    led = ServingLedger(clock=fc)
    with led.measure("host"):
        fc.tick(0.01)
        led.book_dispatch(0.005, prefill_positions=0, decode_positions=0,
                          total_positions=16, owners=[("t", "batch", 0)])
    snap = led.snapshot()
    # no advanced rows: the span is pure host overhead, no owner is billed
    assert snap["phase_seconds"]["prefill_compute"] == 0.0
    assert snap["phase_seconds"]["host"] == pytest.approx(0.01)
    assert snap["tenants"] == {}
    assert snap["decode_mfu"] is None          # flops not registered
    led.set_decode_flops(2e6, 1e12)
    with led.measure("host"):
        fc.tick(0.01)
        led.book_dispatch(0.004, prefill_positions=0, decode_positions=8,
                          total_positions=16, owners=[("t", "batch", 8)])
    snap = led.snapshot()
    assert snap["decode_mfu"] == pytest.approx(
        decode_mfu(2e6, 8, snap["phase_seconds"]["decode_compute"], 1e12))
    # reset zeros the meters and re-arms the wall clock
    led.reset()
    snap = led.snapshot()
    assert snap["dispatches"] == 0 and snap["tenants"] == {}
    assert snap["wall_seconds"] == 0.0


def test_owner_device_seconds_sum_to_compute_exactly():
    fc = FakeClock()
    led = ServingLedger(clock=fc)
    rng = np.random.RandomState(7)
    for _ in range(50):
        with led.measure("host"):
            fc.tick(0.002)
            pre, dec = int(rng.randint(0, 9)), int(rng.randint(0, 3))
            owners = []
            left = pre + dec
            for i, t in enumerate(("a", "b", "c")):
                take = left if i == 2 else int(rng.randint(0, left + 1))
                owners.append((t, "interactive" if i else "batch", take))
                left -= take
            led.book_dispatch(0.001, prefill_positions=pre,
                              decode_positions=dec,
                              total_positions=16, owners=owners)
    snap = led.snapshot()
    compute = (snap["phase_seconds"]["prefill_compute"]
               + snap["phase_seconds"]["decode_compute"])
    tenant_sum = sum(v["device_seconds"] for v in snap["tenants"].values())
    class_sum = sum(v["device_seconds"] for v in snap["classes"].values())
    assert tenant_sum == pytest.approx(compute, abs=1e-12)
    assert class_sum == pytest.approx(compute, abs=1e-12)
    assert snap["compute_seconds"] == pytest.approx(compute)
    assert sum(v["tokens"] for v in snap["tenants"].values()) == \
        snap["useful_positions"]


# ---- SLO burn-rate monitor (fake clock) ----

def test_burn_monitor_fires_only_when_both_windows_burn():
    fc = FakeClock()
    obs.flight_recorder().clear()
    mon = SLOBurnMonitor(clock=fc, budget=0.05, threshold=14.4,
                         fast_window_s=10.0, slow_window_s=100.0,
                         min_events=5)
    for _ in range(10):                     # healthy history
        mon.observe("interactive", True)
        fc.tick(1.0)
    fc.tick(40.0)
    for _ in range(10):                     # a sharp storm: fast window
        mon.observe("interactive", False)   # burns at 20x...
        fc.tick(0.1)
    snap = mon.snapshot()
    c = snap["classes"]["interactive"]
    assert c["burn_fast"] == pytest.approx(20.0)
    # ...but the slow window still remembers the good events, so the
    # multi-window rule suppresses the page
    assert c["burn_slow"] < 14.4
    assert not c["fired"] and not snap["fired"]
    # age the good events out of the slow window; sustained badness fires
    fc.tick(100.0)
    for _ in range(6):
        mon.observe("interactive", False)
        fc.tick(0.1)
    snap = mon.snapshot()
    assert snap["classes"]["interactive"]["fired"]
    fired = snap["fired"]["interactive"]
    assert fired["burn_fast"] >= 14.4 and fired["burn_slow"] >= 14.4
    events = [e for e in obs.flight_recorder().snapshot()["events"]
              if e["kind"] == "slo_burn"]
    assert len(events) == 1                 # latched: one page, not a storm
    assert events[0]["slo"] == "interactive"
    # an unrelated healthy class never fires
    mon.observe("batch", True)
    assert not mon.snapshot()["classes"]["batch"]["fired"]


def test_burn_monitor_min_events_guard_and_validation():
    fc = FakeClock()
    obs.flight_recorder().clear()
    mon = SLOBurnMonitor(clock=fc, budget=0.05, threshold=14.4,
                         min_events=10)
    for _ in range(9):                      # total outage, but below the
        mon.observe("interactive", False)   # cold-start floor
        fc.tick(0.01)
    c = mon.snapshot()["classes"]["interactive"]
    assert c["burn_fast"] is None and not c["fired"]
    with pytest.raises(ValueError, match="budget"):
        SLOBurnMonitor(budget=0.0)
    with pytest.raises(ValueError, match="threshold"):
        SLOBurnMonitor(threshold=0.0)
    with pytest.raises(ValueError, match="fast"):
        SLOBurnMonitor(fast_window_s=300.0, slow_window_s=60.0)
    with pytest.raises(ValueError, match="min_events"):
        SLOBurnMonitor(min_events=0)


# ---- Prometheus label escaping (ISSUE 11 satellite regression) ----

def test_prom_label_value_injection_is_neutralized():
    from paddle_tpu.obs.prom import (PromBuilder, escape_label_value,
                                     parse_exposition)
    evil = 'x",hack="1"} 99\npdtpu_injected_total 1'
    b = PromBuilder()
    b.family("pdtpu_llm_tenant_device_seconds_total", "counter")
    b.sample("pdtpu_llm_tenant_device_seconds_total", 5,
             labels={"tenant": evil})
    text = b.render()
    # ONE sample line: the crafted value cannot smuggle extra samples,
    # labels, or a second metric into the scrape
    lines = [ln for ln in text.splitlines() if not ln.startswith("#")]
    assert len(lines) == 1
    flat = parse_exposition(text)
    assert len(flat) == 1
    key, value = next(iter(flat.items()))
    assert value == 5.0
    assert escape_label_value(evil) in key
    assert "pdtpu_injected_total" not in flat
    # round-trip stability: parsing the render re-escapes canonically
    assert parse_exposition(text) == flat
    # backslash/newline/quote all survive a full escape->parse cycle
    for v in ('a\\b', 'a"b', 'a\nb', 'a\\"b\\n'):
        bb = PromBuilder()
        bb.sample("m", 1, labels={"l": v})
        assert parse_exposition(bb.render()) == {
            'm{l="' + escape_label_value(v) + '"}': 1.0}


def test_metrics_render_with_hostile_tenant_id_stays_parseable():
    from paddle_tpu.obs.prom import parse_exposition
    from paddle_tpu.serving.metrics import LLMMetrics
    fc = FakeClock()
    led = ServingLedger(clock=fc)
    with led.measure("host"):
        fc.tick(0.01)
        led.book_dispatch(0.004, prefill_positions=4, decode_positions=0,
                          total_positions=16,
                          owners=[('t"evil\n', "interactive", 4)])
    m = LLMMetrics()
    m.ledger = led
    text = m.render()
    flat = parse_exposition(text)
    hits = [k for k in flat
            if k.startswith("pdtpu_llm_tenant_device_seconds_total")]
    assert len(hits) == 1 and flat[hits[0]] > 0
    assert not any(ln == "evil" for ln in text.splitlines())


# ---- time-weighted slot occupancy (ISSUE 11 satellite) ----

def test_time_weighted_occupancy_average():
    from paddle_tpu.serving.metrics import LLMMetrics
    m = LLMMetrics()
    m.set_slots(0, 4)
    assert m.snapshot()["slot_occupancy_avg"] is None   # no window yet
    m.observe_occupancy(10.0)
    m.set_slots(4, 4)
    m.observe_occupancy(11.0)      # level 0.0 held for 1s
    m.set_slots(2, 4)
    m.observe_occupancy(13.0)      # level 1.0 held for 2s
    snap = m.snapshot()
    assert snap["slot_occupancy_avg"] == pytest.approx(2.0 / 3.0)
    assert snap["slot_occupancy"] == pytest.approx(0.5)  # instantaneous
    text = m.render()
    assert "pdtpu_llm_slot_occupancy_avg 0.6667" in text
    # a backwards/zero dt observation is a no-op, not a negative credit
    m.observe_occupancy(13.0)
    assert m.snapshot()["slot_occupancy_avg"] == pytest.approx(2.0 / 3.0)


# ---- LLM engine integration (production pump, ticking SimClock) ----

def test_llm_pump_phases_tile_wall_and_tenants_pay_compute(gpt_tiny):
    """The acceptance reconciliation: with economics armed, the serving
    ledger's phase seconds tile the engine's measured wall clock within
    1%, per-tenant (and per-class) device seconds sum EXACTLY to
    prefill_compute + decode_compute, and the rendered exposition
    carries the economics families."""
    from paddle_tpu import serving
    from paddle_tpu.obs.prom import parse_exposition

    clock = _ticking_sim_clock()
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4,
                                economics=True),
        clock=clock)
    assert eng.ledger is not None and eng.burn is not None
    rng = np.random.RandomState(0)
    handles = []
    for i in range(4):
        handles.append(eng.submit(
            rng.randint(1, 400, size=3 + i).astype(np.int32),
            max_new_tokens=3, tenant=f"t{i % 2}",
            slo="interactive" if i % 2 else "batch"))
        eng.pump()
    while eng.has_work():
        eng.pump()
    for h in handles:
        h.result(timeout=0)

    snap = eng.ledger.snapshot()
    ph = snap["phase_seconds"]
    assert set(ph) == set(SERVING_LEDGER_PHASES)
    assert ph["host"] > 0 and snap["compute_seconds"] > 0
    # tiling: booked phases (idle = residual) reconcile with wall within 1%
    assert sum(ph.values()) == pytest.approx(snap["wall_seconds"],
                                             rel=0.01, abs=1e-9)
    # cost metering: both tenants and both classes present, and their
    # device seconds sum to the compute phases exactly
    assert set(snap["tenants"]) == {"t0", "t1"}
    assert set(snap["classes"]) == {"interactive", "batch"}
    tenant_sum = sum(v["device_seconds"] for v in snap["tenants"].values())
    class_sum = sum(v["device_seconds"] for v in snap["classes"].values())
    assert tenant_sum == pytest.approx(snap["compute_seconds"], abs=1e-9)
    assert class_sum == pytest.approx(snap["compute_seconds"], abs=1e-9)
    # token economics over the fixed-width unified step
    assert snap["dispatches"] > 0
    assert 0 < snap["token_efficiency"] <= 1.0
    assert snap["useful_positions"] == (snap["prefill_tokens"]
                                        + snap["decode_tokens"])
    assert snap["total_positions"] == snap["dispatches"] * 2 * 16

    text = eng.metrics.render()
    flat = parse_exposition(text)
    for fam in ("pdtpu_llm_phase_seconds_total", "pdtpu_llm_wall_seconds",
                "pdtpu_llm_token_efficiency", "pdtpu_llm_host_fraction",
                "pdtpu_llm_tenant_device_seconds_total",
                "pdtpu_llm_class_device_seconds_total",
                "pdtpu_llm_slot_occupancy_avg"):
        assert any(k.startswith(fam) for k in flat), fam
    assert 'pdtpu_llm_tenant_device_seconds_total{tenant="t0"}' in flat
    assert "economics" in eng.metrics.snapshot()
    eng.stop()


def test_streams_bit_identical_with_ledger_armed(gpt_tiny):
    """Economics must observe, never perturb: every stream from an armed
    engine equals one-shot greedy generate() bit-for-bit, and a default
    engine pays one predicate per hook (ledger and burn are both None)."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate

    prompts = [np.arange(1, 5, dtype=np.int32),
               np.arange(11, 15, dtype=np.int32)]
    ref = np.asarray(generate(gpt_tiny, np.stack(prompts),
                              max_new_tokens=4).numpy())[:, 4:]
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4,
                                economics=True,
                                slo_ttft_target_ms={"batch": 50.0}),
        clock=serving.SimClock())
    handles = [eng.submit(p, max_new_tokens=4) for p in prompts]
    while eng.has_work():
        eng.pump()
    for h, r in zip(handles, ref):
        assert np.array_equal(h.result(timeout=0), r)
    assert eng.ledger.snapshot()["dispatches"] > 0
    eng.stop()

    # default config: economics fully disabled, nothing attached
    off = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4),
        clock=serving.SimClock())
    assert off.ledger is None and off.burn is None
    assert off.metrics.ledger is None and off.metrics.burn is None
    assert "economics" not in off.metrics.snapshot()
    off.stop()


def test_llm_config_validates_economics_knobs():
    from paddle_tpu.serving import LLMEngineConfig
    with pytest.raises(ValueError, match="slo_burn_budget"):
        LLMEngineConfig(slo_burn_budget=1.5)
    with pytest.raises(ValueError, match="slo_burn windows"):
        LLMEngineConfig(slo_burn_fast_window_s=300.0,
                        slo_burn_slow_window_s=60.0)
    with pytest.raises(ValueError, match="slo_ttft_target_ms keys"):
        LLMEngineConfig(slo_ttft_target_ms={"gold": 5.0})
    with pytest.raises(ValueError, match="must be > 0"):
        LLMEngineConfig(slo_ttft_target_ms={"interactive": 0.0})


# ---- stateless BatchingEngine: pad-waste economics + /debug/costs ----

@pytest.mark.serving
def test_batching_engine_pad_waste_and_debug_costs_endpoint():
    """The pow2-padded predict dispatch meters real rows as useful
    positions and pad rows as waste; /debug/costs serves the ledger
    snapshot (and null burn state) per engine."""
    import urllib.request
    from paddle_tpu import serving

    eng = serving.BatchingEngine(
        lambda args: [np.asarray(args[0], np.float32) * 2.0],
        serving.EngineConfig(max_batch_size=8, max_wait_ms=1.0,
                             economics=True))
    server = serving.ServingServer(eng, port=0).start()
    try:
        x = np.ones((3, 2), np.float32)
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict",
            data=json.dumps({"inputs": [x.tolist()]}).encode(),
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/costs",
                timeout=30) as r:
            costs = json.loads(r.read())
        econ = costs["predict"]["economics"]
        assert econ["dispatches"] >= 1
        assert econ["useful_positions"] == 3
        assert econ["total_positions"] == 4          # pow2 pad: 3 -> 4
        assert econ["token_efficiency"] == pytest.approx(0.75)
        assert sum(econ["phase_seconds"].values()) == pytest.approx(
            econ["wall_seconds"], rel=0.01, abs=1e-6)
        assert costs["predict"]["slo_burn"] is None  # no SLO classes here
    finally:
        server.stop()


# ---- the fault-matrix scenario (tools/check_fault_matrix.py) ----

@pytest.mark.fault_matrix
def test_dispatch_storm_fires_slo_burn_before_breaker(gpt_tiny, tmp_path,
                                                      monkeypatch):
    """Dispatch storm: every step and every blame probe raises, so each
    round fails ALL active interactive requests (non-attributable ->
    engine failure). The burn monitor sees the bad outcomes BEFORE each
    round charges the breaker, so the latched `slo_burn` flight event
    lands in the ring — and in the breaker-open black-box dump — with a
    smaller seq than the `breaker_open` it predicts. The postmortem CLI
    isolates the alert with --kind 'slo_*'."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan

    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    obs.flight_recorder().clear()
    # round 1: step idx 0 raises (dispatch_retries=0), blame probes idx
    # 1/2 raise -> non-attributable -> engine failure #1 (2 bad events,
    # below min_events=3: no alert). round 2: idx 3 + probes 4/5 raise
    # -> the round's FIRST bad observation is event #3: burn = 20x over
    # both windows >= 14.4 -> slo_burn fires; THEN the round's
    # record_failure opens the breaker (threshold 2) and dumps the ring.
    plan = FaultPlan.from_spec(
        "dispatch_raise@0;dispatch_raise@1;dispatch_raise@2;"
        "dispatch_raise@3;dispatch_raise@4;dispatch_raise@5")
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4,
                                dispatch_retries=0, breaker_threshold=2,
                                economics=True, slo_burn_min_events=3),
        clock=serving.SimClock(), fault_plan=plan)
    r0 = [eng.submit([i + 1, i + 2], max_new_tokens=4, slo="interactive")
          for i in range(2)]
    eng.pump()                              # engine failure #1
    for h in r0:
        with pytest.raises(serving.DispatchFailedError):
            h.result(timeout=0)
    assert not eng.broken
    assert not eng.burn.snapshot()["classes"]["interactive"]["fired"]
    r1 = [eng.submit([i + 5, i + 6], max_new_tokens=4, slo="interactive")
          for i in range(2)]
    eng.pump()                              # burn fires, THEN breaker opens
    assert eng.broken
    for h in r1:
        with pytest.raises(serving.DispatchFailedError):
            h.result(timeout=0)
    burn_snap = eng.burn.snapshot()
    assert burn_snap["classes"]["interactive"]["fired"]
    assert burn_snap["fired"]["interactive"]["burn_fast"] >= 14.4

    # the breaker-open dump already carries the earlier slo_burn event
    dump_path = tmp_path / f"pdtpu_flight_{os.getpid()}.json"
    assert dump_path.exists(), "breaker open must dump the flight ring"
    doc = json.loads(dump_path.read_text())
    events = doc["events"]
    kinds = [e["kind"] for e in events]
    assert "slo_burn" in kinds and "breaker_open" in kinds
    burn_ev = next(e for e in events if e["kind"] == "slo_burn")
    brk_ev = next(e for e in events if e["kind"] == "breaker_open")
    assert burn_ev["seq"] < brk_ev["seq"], \
        "the alert must precede the breaker it predicts"
    assert burn_ev["slo"] == "interactive"
    assert burn_ev["burn_fast"] >= 14.4 and burn_ev["burn_slow"] >= 14.4

    # postmortem CLI: --kind 'slo_*' isolates the alert
    r = subprocess.run(
        [sys.executable, CLI, str(dump_path), "--kind", "slo_*"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    # the dump header names the dump reason (breaker_open:llm); the
    # FILTERED event listing must carry only the slo_* events
    event_lines = [ln for ln in r.stdout.splitlines() if "s " in ln
                   and ln.lstrip().startswith("[")]
    assert event_lines and all("slo_burn" in ln for ln in event_lines)
    assert not any("breaker_open" in ln for ln in event_lines)
    eng.stop()
