"""Regression tests for the round-1 advisor findings (ADVICE.md)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_parallel_ce_ignore_index():
    """_c_softmax_with_cross_entropy must zero the loss for ignore_index
    tokens (ADVICE medium: padding tokens silently trained on)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from paddle_tpu.distributed.collective import (
        _c_softmax_with_cross_entropy, axis_context)

    rng = np.random.RandomState(0)
    V = 16
    logits = rng.randn(4, V).astype(np.float32)
    labels = np.array([1, -100, 7, -100], dtype=np.int32)

    devs = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devs, ("model",))

    def f(lg, lb):
        with axis_context(("model",)):
            out = _c_softmax_with_cross_entropy(
                Tensor(lg), Tensor(lb), group="model", ignore_index=-100)
        return out.data

    loss = shard_map(f, mesh=mesh, in_specs=(P(None, "model"), P()),
                     out_specs=P())(jnp.asarray(logits), jnp.asarray(labels))
    loss = np.asarray(loss)
    # ignored rows contribute exactly zero
    np.testing.assert_allclose(loss[[1, 3]], 0.0, atol=0)
    # non-ignored rows match the dense reference
    ref = -np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
    np.testing.assert_allclose(loss[0], ref[0, 1], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(loss[2], ref[2, 7], rtol=1e-5, atol=1e-5)


def test_allreduce_prod_sign_and_zero():
    """ReduceOp.PROD must be sign-correct and handle zeros (ADVICE via
    VERDICT weak #5)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from paddle_tpu.distributed.collective import (
        ReduceOp, all_reduce, axis_context)

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("x",))
    # per-device rows; product across devices has negatives and a zero column
    vals = np.array([[2.0, -1.0, 3.0],
                     [-3.0, -2.0, 0.0],
                     [1.0, -1.0, 2.0],
                     [-1.0, 4.0, 5.0]], dtype=np.float32)
    expect = vals.prod(axis=0)

    def f(a):
        with axis_context(("x",)):
            t = Tensor(a)
            all_reduce(t, op=ReduceOp.PROD, group="x")
        return t.data

    out = shard_map(f, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))(
        jnp.asarray(vals))
    # every rank holds the full product
    np.testing.assert_allclose(np.asarray(out)[0], expect, rtol=1e-5)


def test_grad_scaler_no_double_unscale():
    """scaler.unscale_(opt) -> clip -> scaler.step(opt) must divide the grads
    by the scale exactly once (ADVICE medium)."""
    from paddle_tpu import optimizer as optim
    from paddle_tpu.amp import GradScaler

    from paddle_tpu.core.tensor import Parameter
    p = Parameter(np.array([1.0, 2.0], dtype=np.float32))
    opt = optim.SGD(learning_rate=1.0, parameters=[p])
    scaler = GradScaler(init_loss_scaling=8.0)

    loss = (p * paddle.to_tensor(np.array([1.0, 1.0], np.float32))).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    # grad is now 8.0 per element
    scaler.unscale_(opt)
    g1 = np.asarray(p.grad.data).copy()
    np.testing.assert_allclose(g1, [1.0, 1.0])
    scaler.step(opt)  # must NOT unscale again
    # sgd with lr=1: p_new = p - 1.0 * grad(unscaled once)
    np.testing.assert_allclose(np.asarray(p.data), [0.0, 1.0], rtol=1e-6)


def test_grad_scaler_unscale_without_step_recovers():
    """unscale_ without a following step() must not permanently disable
    unscaling for that optimizer: update() clears the per-step bookkeeping."""
    from paddle_tpu import optimizer as optim
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.core.tensor import Parameter

    p = Parameter(np.array([1.0], dtype=np.float32))
    opt = optim.SGD(learning_rate=1.0, parameters=[p])
    scaler = GradScaler(init_loss_scaling=4.0)
    # iteration 1: unscale, then skip step (e.g. user bails on clip failure)
    p.grad = paddle.to_tensor(np.array([4.0], np.float32))
    scaler.unscale_(opt)
    scaler.update()
    # iteration 2: unscale_ must run again
    p.grad = paddle.to_tensor(np.array([4.0], np.float32))
    scaler.unscale_(opt)
    np.testing.assert_allclose(np.asarray(p.grad.data), [1.0])


def test_allreduce_prod_int_exact():
    """Integer PROD must be exact (no exp/log round-trip truncation)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    from paddle_tpu.distributed.collective import (
        ReduceOp, all_reduce, axis_context)

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("x",))
    vals = np.array([[2, 7], [3, 1], [1, 5], [7, 3]], dtype=np.int32)

    def f(a):
        with axis_context(("x",)):
            t = Tensor(a)
            all_reduce(t, op=ReduceOp.PROD, group="x")
        return t.data

    out = shard_map(f, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))(
        jnp.asarray(vals))
    assert np.asarray(out).dtype == np.int32
    np.testing.assert_array_equal(np.asarray(out)[0], [42, 105])


def test_setitem_prior_consumers_keep_grads():
    """Ops that consumed a tensor BEFORE an in-place write keep their
    gradient path to the original producer (in_links snapshot)."""
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2.0
    z = y * 3.0          # consumes pre-write y
    y[0:1] = 0.0         # in-place write rebinds y's node
    z.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(np.asarray(x.grad.data), [6.0, 6.0, 6.0])


def test_setitem_pre_and_post_consumers():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2.0
    z = y * 3.0          # pre-write consumer: d/dx = 6 everywhere
    y[0:1] = 0.0         # write kills x's path through y[0]
    w = y * 5.0          # post-write consumer: d/dx = 10 except idx 0
    (z.sum() + w.sum()).backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [6.0, 16.0, 16.0])


def test_split_indivisible_raises():
    x = paddle.to_tensor(np.arange(10, dtype=np.float32))
    with pytest.raises(ValueError):
        paddle.split(x, 3)


def test_setitem_grad_flows():
    """__setitem__ on a non-leaf participates in autograd (ADVICE low)."""
    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    y = x * 2.0              # non-leaf
    v = paddle.to_tensor(np.array([5.0], np.float32), stop_gradient=False)
    y[1:2] = v
    loss = (y * y).sum()
    loss.backward()
    # dy/dx: positions 0,2,3 give d((2x)^2)/dx = 8x = 8; position 1 overwritten
    np.testing.assert_allclose(np.asarray(x.grad.data), [8.0, 0.0, 8.0, 8.0])
    # grad w.r.t. the assigned value: d(v^2)/dv = 2v = 10
    np.testing.assert_allclose(np.asarray(v.grad.data), [10.0])


def test_setitem_leaf_requires_grad_raises():
    p = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with pytest.raises(RuntimeError):
        p[0] = 2.0


def test_setitem_no_grad_ok():
    p = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        p[0] = 2.0
    np.testing.assert_allclose(np.asarray(p.data), [2.0, 1.0, 1.0])


# ---- round-3 advisor findings ----

def test_inplace_tanh_grad_on_nonleaf():
    """ADVICE r2 high: tanh_ on a non-leaf must contribute its Jacobian."""
    x = Tensor(np.array([0.3, -0.7], np.float32), stop_gradient=False)
    y = x * 2.0
    paddle.tanh_(y)
    z = (y * y).sum()
    z.backward()
    # d/dx sum(tanh(2x)^2) = 2*tanh(2x) * (1-tanh(2x)^2) * 2
    t = np.tanh(np.array([0.6, -1.4], np.float32))
    ref = 2.0 * t * (1.0 - t * t) * 2.0
    np.testing.assert_allclose(np.asarray(x.grad.data), ref, rtol=1e-5)


def test_inplace_tanh_leaf_raises():
    x = Tensor(np.ones(2, np.float32), stop_gradient=False)
    with pytest.raises(RuntimeError):
        paddle.tanh_(x)


def test_inplace_scatter_grad_on_nonleaf():
    """scatter_ overwrite must BLOCK grad into the overwritten rows."""
    x = Tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    y = x * 3.0
    upd = Tensor(np.zeros((1, 2), np.float32))
    paddle.scatter_(y, Tensor(np.array([1], np.int64)), upd)
    y.sum().backward()
    g = np.asarray(x.grad.data)
    # row 1 was overwritten by a constant: no grad flows to x there
    np.testing.assert_allclose(g[1], 0.0)
    np.testing.assert_allclose(g[[0, 2]], 3.0)


def test_inplace_squeeze_unsqueeze_grad():
    x = Tensor(np.ones((2, 1, 3), np.float32), stop_gradient=False)
    y = x * 5.0
    paddle.squeeze_(y, axis=1)
    assert tuple(y.shape) == (2, 3)
    (y * 2.0).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), 10.0)


def test_spectral_norm_zero_power_iterations():
    """ADVICE r2 low: n_power_iterations=0 must reuse stored u, not crash."""
    from paddle_tpu.nn.utils import spectral_norm
    lin = paddle.nn.Linear(4, 3)
    spectral_norm(lin, n_power_iterations=0)
    out = lin(Tensor(np.ones((2, 4), np.float32)))
    assert np.isfinite(np.asarray(out.data)).all()


def test_l1decay_applies_to_sparse_grads():
    """ADVICE r2 low: L1 regularization must not be skipped on the
    SelectedRows fast path."""
    from paddle_tpu.regularizer import L1Decay
    emb = paddle.nn.Embedding(8, 4, sparse=True)
    w0 = np.asarray(emb.weight.data).copy()
    opt2 = paddle.optimizer.SGD(learning_rate=1.0,
                                parameters=emb.parameters(),
                                weight_decay=L1Decay(0.5))
    ids = Tensor(np.array([2, 5], np.int64))
    emb(ids).sum().backward()
    assert "SelectedRows" in type(emb.weight.grad).__name__
    opt2.step()
    w1 = np.asarray(emb.weight.data)
    # touched rows: grad 1.0 + 0.5*sign(w); untouched rows unchanged
    exp = w0[2] - (1.0 + 0.5 * np.sign(w0[2]))
    np.testing.assert_allclose(w1[2], exp, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w1[0], w0[0])


def test_dynamic_batch_nonbatched_output_raises():
    """ADVICE r2 low: chunked dynamic batch + reduction output must raise,
    not silently return the first chunk's value."""
    import tempfile, os
    from paddle_tpu.inference import export_model, load_predictor

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(4, 2)

        def forward(self, x):
            o = self.lin(x)
            return o.mean()  # batch reduction → non-batched output

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m")
        export_model(M(), [Tensor(np.ones((2, 4), np.float32))], path)
        pred = load_predictor(path)
        with pytest.raises(ValueError, match="non-batched"):
            pred.run([np.ones((5, 4), np.float32)])


def test_l1decay_sparse_duplicate_rows_single_penalty():
    """A token seen k times must get the L1 penalty once, not k times."""
    from paddle_tpu.regularizer import L1Decay
    emb = paddle.nn.Embedding(8, 4, sparse=True)
    w0 = np.asarray(emb.weight.data).copy()
    opt = paddle.optimizer.SGD(learning_rate=1.0,
                               parameters=emb.parameters(),
                               weight_decay=L1Decay(0.5))
    emb(Tensor(np.array([2, 2], np.int64))).sum().backward()
    opt.step()
    w1 = np.asarray(emb.weight.data)
    # grad 2.0 (row hit twice) + ONE L1 pull
    exp = w0[2] - (2.0 + 0.5 * np.sign(w0[2]))
    np.testing.assert_allclose(w1[2], exp, rtol=1e-5, atol=1e-6)


def test_l1decay_sparse_adam_nonlazy_no_double_penalty():
    """Adam lazy_mode=False declines the sparse rule → densify path must
    apply L1 exactly once (not once folded + once in _reg_grad)."""
    from paddle_tpu.regularizer import L1Decay
    emb = paddle.nn.Embedding(6, 3, sparse=True)
    w0 = np.asarray(emb.weight.data).copy()
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=emb.parameters(),
                                weight_decay=L1Decay(0.5))
    emb(Tensor(np.array([1], np.int64))).sum().backward()
    opt.step()
    w1 = np.asarray(emb.weight.data)
    # dense-path reference: g = onehot + 0.5*sign(w) everywhere, one step of Adam
    g = np.zeros_like(w0)
    g[1] = 1.0
    g = g + 0.5 * np.sign(w0)
    m1 = 0.1 * g
    m2 = 0.001 * g * g
    upd = (m1 / (1 - 0.9)) / (np.sqrt(m2 / (1 - 0.999)) + 1e-8)
    exp = w0 - 0.1 * upd
    np.testing.assert_allclose(w1, exp, rtol=1e-4, atol=1e-5)


def test_inplace_reshape_grad_on_nonleaf():
    x = Tensor(np.ones((2, 3), np.float32), stop_gradient=False)
    y = x * 2.0
    paddle.reshape_(y, [6])
    (y * 3.0).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), 6.0)


def test_inplace_zero_blocks_grad_on_nonleaf():
    x = Tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 3.0
    paddle.zero_(y)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), 0.0)
    np.testing.assert_allclose(np.asarray(y.data), 0.0)


def test_inplace_fill_no_grad_on_leaf_ok():
    p = Tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        paddle.fill_(p, 7.0)
    np.testing.assert_allclose(np.asarray(p.data), 7.0)
    with pytest.raises(RuntimeError):
        paddle.fill_(p, 1.0)  # leaf requiring grad outside no_grad


def test_dynamic_batch_constant_output_passes_through():
    """A chunk-invariant non-batched output (constant table) must NOT be
    rejected — only batch reductions are unreassemblable."""
    import tempfile, os
    from paddle_tpu.inference import export_model, load_predictor

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(4, 2)

        def forward(self, x):
            table = self.lin.weight * 1.0  # batch-independent output
            return self.lin(x), table

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m")
        export_model(M(), [Tensor(np.ones((2, 4), np.float32))], path)
        pred = load_predictor(path)
        outs = pred.run([np.ones((6, 4), np.float32)])
        assert outs[0].shape[0] == 6
        assert outs[1].shape == (4, 2)


# ---- round-4 advisor findings ----

def test_ps_adagrad_slots_survive_save_load(tmp_path):
    """ADVICE r3 medium: a PS save/load roundtrip must persist AdaGrad
    accumulators — otherwise the effective per-row LR silently resets."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        TheOnePSRuntime)
    ids = np.array([3, 7], np.int64)
    g = np.ones((2, 4), np.float32)

    def push_twice(rt, roundtrip):
        rt.client.create_table("emb", 4, rule="adagrad", lr=0.1)
        rt.client.pull_sparse("emb", ids)
        rt.client.push_sparse("emb", ids, g)
        if roundtrip:
            d = str(tmp_path / "ckpt")
            rt.save(d)
            rt = TheOnePSRuntime(n_shards=3)  # re-shard on load too
            rt.load(d)
        rt.client.push_sparse("emb", ids, g)
        return rt.client.pull_sparse("emb", ids)

    cont = push_twice(TheOnePSRuntime(n_shards=2), roundtrip=False)
    saved = push_twice(TheOnePSRuntime(n_shards=2), roundtrip=True)
    np.testing.assert_allclose(saved, cont, rtol=1e-6, atol=1e-7)


def test_gpt_init_cache_position_bound():
    """ADVICE r3 low: decoding past the learned position table must raise,
    not silently clamp to the last position embedding."""
    from paddle_tpu.models.gpt import GPTForCausalLM
    model = GPTForCausalLM.from_preset("gpt2-tiny",
                                       max_position_embeddings=16)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.init_cache(1, 32)
    model.init_cache(1, 16)  # at the bound: fine


def test_gpt_cached_forward_dropout_parity_training():
    """ADVICE r3 low: forward_with_cache on a training-mode model must
    apply the SAME dropout calls (embedding + both residual branches) as
    forward(). With p=0.5 and a reset seed, identical call order/shapes
    draw identical masks, so the logits must agree exactly — a missing or
    extra dropout call desynchronizes the RNG stream and the test fails."""
    from paddle_tpu.models.gpt import GPTForCausalLM
    model = GPTForCausalLM.from_preset("gpt2-tiny",
                                       hidden_dropout_prob=0.5)
    model.train()
    ids = Tensor(np.arange(6, dtype=np.int64)[None, :])
    paddle.seed(1234)
    ref = np.asarray(model(ids).data)
    # sanity: the run is genuinely stochastic (different seed => different)
    paddle.seed(99)
    other = np.asarray(model(ids).data)
    assert not np.allclose(ref, other)
    paddle.seed(1234)
    caches = model.init_cache(1, 8)
    logits, _ = model.forward_with_cache(ids, caches, 0)
    np.testing.assert_allclose(np.asarray(logits.data), ref,
                               rtol=1e-5, atol=1e-5)


def test_dynamic_batch_single_padded_chunk_constant_ok():
    """ADVICE r3 low: batch < exported batch with a chunk-invariant
    constant output must pass (probed with duplicated-row padding), while
    a batch reduction must still raise."""
    import tempfile, os
    from paddle_tpu.inference import export_model, load_predictor

    class Const(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(5, 2)

        def forward(self, x):
            return self.lin(x), self.lin.weight * 1.0

    class Reduce(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(5, 2)

        def forward(self, x):
            return self.lin(x), self.lin(x).mean()

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "c")
        export_model(Const(), [Tensor(np.ones((4, 5), np.float32))], path)
        pred = load_predictor(path)
        outs = pred.run([np.ones((1, 5), np.float32)])  # batch 1 < 4
        assert outs[0].shape[0] == 1
        assert outs[1].shape == (5, 2)

        path = os.path.join(d, "r")
        export_model(Reduce(), [Tensor(np.ones((4, 5), np.float32))], path)
        pred = load_predictor(path)
        with pytest.raises(ValueError, match="non-batched"):
            pred.run([np.ones((1, 5), np.float32)])


def test_dynamic_batch_zero_warmup_reduction_still_raises():
    """A zeros warmup batch must not latch a batch reduction as
    pad-invariant: the probe perturbs padding rows (+1), so the reduction
    is caught even when the real rows are all-zero."""
    import tempfile, os
    from paddle_tpu.inference import export_model, load_predictor

    class Reduce(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(5, 2)

        def forward(self, x):
            return self.lin(x), self.lin(x).mean()

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "r")
        export_model(Reduce(), [Tensor(np.ones((4, 5), np.float32))], path)
        pred = load_predictor(path)
        with pytest.raises(ValueError, match="non-batched"):
            pred.run([np.zeros((1, 5), np.float32)])  # zeros warmup
        with pytest.raises(ValueError, match="non-batched"):
            pred.run([np.ones((1, 5), np.float32)])   # still raises after
