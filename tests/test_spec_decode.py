"""Speculative decoding inside the unified step (ISSUE 17): a draft
model proposes K tokens per decode row from its OWN slot-paged KV pool
(one on-device scan dispatch), the target verifies every position of the
window in ONE unified-step dispatch, and greedy acceptance — longest
matching prefix plus the target's corrective token — makes the output
bit-identical to plain greedy decode BY CONSTRUCTION. These tests pin
that construction: bit-identity with matched AND mismatched drafts,
EOS/max-token truncation inside a window, draft-pool rewind accounting,
the serving-ledger draft_compute meters, the draft failure protocol
(quarantine without charging the target breaker), and router failover
mid-draft-window.

Every scheduler test runs the PRODUCTION pump under a SimClock —
scripted instants, no sleeps, no thread flake."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


@pytest.fixture(scope="module")
def gpt_tiny_alt():
    """Same architecture, DIFFERENT weights: a deliberately bad draft
    whose proposals the target mostly rejects."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(123)
    return GPTForCausalLM.from_preset("gpt2-tiny")


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    from paddle_tpu.utils.fault_injection import set_global_plan
    set_global_plan(None)
    yield
    set_global_plan(None)


def _engine(model, clock, draft=None, **cfg_kw):
    from paddle_tpu import serving
    kw = dict(num_slots=2, block_len=8, n_blocks=4, max_queue_depth=64)
    kw.update(cfg_kw)
    return serving.LLMEngine(model, serving.LLMEngineConfig(**kw),
                             clock=clock, draft_model=draft)


def _drain(eng, clock=None, dt=0.01):
    steps = 0
    while eng.has_work():
        if clock is not None:
            clock.advance(dt)
        eng.pump()
        steps += 1
        assert steps < 2000, "engine failed to converge"


def _ref(model, prompt, max_new, eos=None):
    from paddle_tpu.models.generation import generate
    out = generate(model, np.asarray(prompt, np.int32)[None, :],
                   max_new_tokens=max_new, eos_token_id=eos)
    return np.asarray(out.numpy())[0, len(prompt):]


# ---- the acceptance proof: bit-identical, fewer decode iterations ----

def test_spec_bit_identical_with_fewer_decode_iterations(gpt_tiny):
    """The same staggered 4-request trace through a plain engine and a
    spec engine (draft == target, so greedy acceptance is deterministic):
    every stream must match one-shot generate() bit-for-bit on BOTH
    engines, and the spec engine must commit the identical token totals
    in at most half the decode iterations — the dispatch-count collapse
    that IS the perf win."""
    from paddle_tpu import serving

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 500, size=(6,)).astype(np.int32)
               for _ in range(4)]
    refs = [_ref(gpt_tiny, p, 16) for p in prompts]

    iters = {}
    for mode, draft in (("plain", None), ("spec", gpt_tiny)):
        clock = serving.SimClock()
        eng = _engine(gpt_tiny, clock, draft=draft)
        handles = []
        for p in prompts:
            clock.advance(0.01)
            handles.append(eng.submit(p, max_new_tokens=16))
            eng.pump()
        _drain(eng, clock)
        for h, r in zip(handles, refs):
            assert np.array_equal(h.result(timeout=0), r)
        iters[mode] = eng.decode_iterations
        eng.pool.check_balance()
        if draft is not None:
            eng.draft_pool.check_balance()
            assert eng.draft_pool.active_slots() == 0
            snap = eng.metrics.snapshot()
            # draft == target: every window accepts everything
            assert snap["spec_accept_rate"] == 1.0
            assert snap["spec_windows"] == eng.spec_windows > 0
            assert snap["spec_drafted"] == snap["spec_accepted"] > 0
            assert snap["spec_draft_quarantines"] == 0
        eng.stop()

    assert iters["spec"] <= 0.5 * iters["plain"], iters


def test_spec_mismatched_draft_still_bit_identical(gpt_tiny, gpt_tiny_alt):
    """A draft with DIFFERENT weights proposes mostly-wrong windows; the
    verify step's corrective token keeps every stream bit-identical to
    plain greedy decode anyway — acceptance only changes how many tokens
    each dispatch commits, never which tokens."""
    from paddle_tpu import serving

    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 500, size=(s,)).astype(np.int32)
               for s in (4, 7, 11)]
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, draft=gpt_tiny_alt)
    handles = [eng.submit(p, max_new_tokens=12) for p in prompts]
    _drain(eng, clock)
    for p, h in zip(prompts, handles):
        assert np.array_equal(h.result(timeout=0), _ref(gpt_tiny, p, 12))
    snap = eng.metrics.snapshot()
    assert snap["spec_windows"] > 0
    assert 0.0 <= snap["spec_accept_rate"] <= 1.0
    assert snap["spec_accepted"] <= snap["spec_drafted"]
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    assert eng.draft_pool.active_slots() == 0
    eng.stop()


def test_spec_eos_inside_window_truncates_exactly(gpt_tiny):
    """An EOS landing INSIDE a verify window must end the stream at that
    token — identical to where sequential decode stops — and release both
    the target and draft rows."""
    from paddle_tpu import serving

    prompt = np.arange(1, 9, dtype=np.int32)
    ref = _ref(gpt_tiny, prompt, 12)
    eos = int(ref[min(2, len(ref) - 1)])
    j = int(np.argmax(ref == eos))       # stream must end exactly here

    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, draft=gpt_tiny, num_slots=1)
    h = eng.submit(prompt, max_new_tokens=12, eos_token_id=eos)
    _drain(eng, clock)
    got = h.result(timeout=0)
    assert got.shape == (j + 1,) and got[-1] == eos
    assert np.array_equal(got, ref[:j + 1])
    assert eng.pool.free_slots() == 1
    assert eng.draft_pool.active_slots() == 0
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    eng.stop()


def test_spec_capacity_edge_degrades_to_plain_decode(gpt_tiny):
    """A window that would overrun the slot's block capacity is simply
    not proposed: near the end of a capacity-exact stream the engine
    degrades to plain decode for the tail and still finishes
    bit-identically, with both pools balanced."""
    from paddle_tpu import serving

    prompt = np.arange(1, 7, dtype=np.int32)          # 6 + 6 == capacity
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, draft=gpt_tiny, num_slots=1,
                  block_len=4, n_blocks=3)
    assert eng.pool.capacity == 12
    h = eng.submit(prompt, max_new_tokens=6)
    _drain(eng, clock)
    assert np.array_equal(h.result(timeout=0), _ref(gpt_tiny, prompt, 6))
    # at least one window ran before the capacity guard kicked in
    assert eng.spec_windows >= 1
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    eng.stop()


def test_spec_prefix_cache_warm_hit_bit_identical(gpt_tiny):
    """Target and draft prefix caches are page-congruent (same block_len,
    same spans): a shared-prefix sibling attaches cached blocks on BOTH
    sides, skips the same token span, and its spec-decoded stream is
    still bit-identical to one-shot generate()."""
    from paddle_tpu import serving

    rng = np.random.RandomState(11)
    shared = rng.randint(1, 500, size=(16,)).astype(np.int32)  # 2 blocks
    sfx = [rng.randint(1, 500, size=(4,)).astype(np.int32) for _ in range(2)]
    prompts = [np.concatenate([shared, s]) for s in sfx]

    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, draft=gpt_tiny)
    assert eng.draft_prefix_cache is not None
    assert eng.draft_prefix_cache.snapshot()["name"] == "draft"
    h1 = eng.submit(prompts[0], max_new_tokens=8)
    _drain(eng, clock)
    h2 = eng.submit(prompts[1], max_new_tokens=8)     # warm: prefix cached
    _drain(eng, clock)
    for p, h in zip(prompts, (h1, h2)):
        assert np.array_equal(h.result(timeout=0), _ref(gpt_tiny, p, 8))
    assert eng.metrics.snapshot()["prefix_hits"] >= 1
    assert eng.draft_prefix_cache.snapshot()["hits"] >= 1
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    eng.stop()


# ---- draft-pool rewind (the rollback primitive) ----

def test_rewind_length_returns_pages_and_balances():
    import jax.numpy as jnp
    from paddle_tpu.serving.llm import SlotPagedKVPool

    def init_cache(b, max_len):
        return [(jnp.zeros((b, 2, max_len, 3), jnp.float32),
                 jnp.zeros((b, 2, max_len, 3), jnp.float32))]

    p = SlotPagedKVPool(init_cache, 2, 4, 3)
    s = p.allocate(12)
    p.set_length(s, 10)                   # 3 blocks claimed
    assert len(p.block_table[s]) == 3
    freed0 = p.stats["blocks_freed"]
    p.rewind_length(s, 5)                 # back to 2 blocks
    assert int(p.lengths[s]) == 5
    assert len(p.block_table[s]) == 2
    assert p.stats["blocks_freed"] == freed0 + 1
    p.rewind_length(s, 5)                 # same length: no-op
    assert p.stats["blocks_freed"] == freed0 + 1
    with pytest.raises(ValueError, match="shrink"):
        p.rewind_length(s, 9)             # growing is set_length's job
    p.set_length(s, 9)                    # the freed page is reusable
    assert len(p.block_table[s]) == 3
    p.free(s)
    p.check_balance()
    with pytest.raises(ValueError, match="not active"):
        p.rewind_length(s, 1)


# ---- serving-ledger economics under spec (ISSUE 11 x ISSUE 17) ----

def test_ledger_books_draft_compute_and_balances():
    """Draft dispatches book into the draft_compute phase with per-owner
    draft_tokens (never the useful-token meter); verify dispatches keep
    the old prefill/decode split. Per-owner device-seconds still sum to
    compute_seconds exactly, and sum(tenant tokens) == useful_positions
    stays intact because draft positions ride their own meter."""
    from paddle_tpu.obs.serving_ledger import ServingLedger

    t = [0.0]
    led = ServingLedger(clock=lambda: t[0])
    # draft proposal: 5 draft positions, zero useful, zero total
    led.book_dispatch(0.01, prefill_positions=0, decode_positions=0,
                      total_positions=0,
                      owners=[("tA", "interactive", 5)], draft_positions=5)
    # the verify step: 5 useful decode positions out of a 32-wide row
    led.book_dispatch(0.03, prefill_positions=0, decode_positions=5,
                      total_positions=32,
                      owners=[("tA", "interactive", 5)],
                      drafted=4, draft_accepted=3)
    t[0] = 0.1
    snap = led.snapshot()
    ph = snap["phase_seconds"]
    assert ph["draft_compute"] == pytest.approx(0.01, abs=1e-12)
    assert ph["decode_compute"] == pytest.approx(0.03, abs=1e-12)
    assert snap["compute_seconds"] == pytest.approx(0.04, abs=1e-12)
    ten = snap["tenants"]["tA"]
    assert ten["device_seconds"] == pytest.approx(snap["compute_seconds"],
                                                  abs=1e-12)
    assert ten["tokens"] == 5 == snap["useful_positions"]
    assert ten["draft_tokens"] == 5 == snap["draft_positions"]
    assert snap["token_efficiency"] == pytest.approx(5 / 32)
    assert snap["spec_drafted"] == 4 and snap["spec_accepted"] == 3
    assert snap["spec_accept_rate"] == pytest.approx(3 / 4)


def test_spec_rejections_measurably_lower_token_efficiency(gpt_tiny,
                                                           gpt_tiny_alt):
    """Rejected draft positions are pad-waste: they stay in the verify
    row's total_positions but never reach the useful count, so the
    mismatched-draft run's ledger token_efficiency must come out strictly
    below the accept-all run's on the same trace — and per-tenant
    device-seconds must sum to compute_seconds under spec in both."""
    from paddle_tpu import serving

    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 500, size=(6,)).astype(np.int32)
               for _ in range(3)]

    def run(draft):
        clock = serving.SimClock()
        eng = _engine(gpt_tiny, clock, draft=draft, num_slots=1,
                      economics=True)
        handles = [eng.submit(p, max_new_tokens=12) for p in prompts]
        _drain(eng, clock)
        for p, h in zip(prompts, handles):
            assert np.array_equal(h.result(timeout=0),
                                  _ref(gpt_tiny, p, 12))
        led = eng.ledger.snapshot()
        eng.stop()
        return led

    led_all = run(gpt_tiny)
    led_rej = run(gpt_tiny_alt)
    assert led_all["spec_accept_rate"] == 1.0
    assert led_rej["spec_accept_rate"] < 1.0
    assert led_rej["token_efficiency"] < led_all["token_efficiency"]
    for led in (led_all, led_rej):
        tenant_s = sum(v["device_seconds"] for v in led["tenants"].values())
        assert tenant_s == pytest.approx(led["compute_seconds"], abs=1e-9)
        assert sum(v["tokens"] for v in led["tenants"].values()) \
            == led["useful_positions"]
        assert sum(v["draft_tokens"] for v in led["tenants"].values()) \
            == led["draft_positions"] > 0


# ---- the draft failure protocol (fault matrix) ----

@pytest.mark.fault_matrix
def test_poisoned_draft_quarantines_draft_only_stream_bit_identical(
        gpt_tiny):
    """poison_request@0:draft fails every DRAFT dispatch carrying
    submit-index 0. Contract: the solo draft probes implicate exactly
    that request, ONLY its draft is quarantined (spec_off — the target
    stream continues as plain decode, bit-identical), the other request
    keeps speculating, the quarantine flight event names the draft stage,
    and the target breaker/dispatch stats are never charged — draft
    dispatches are breaker-exempt by design."""
    from paddle_tpu import serving
    from paddle_tpu.obs.flight_recorder import flight_recorder
    from paddle_tpu.utils.fault_injection import FaultPlan

    flight_recorder().clear()
    plan = FaultPlan.from_spec("poison_request@0:draft")
    clock = serving.SimClock()
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4),
        clock=clock, draft_model=gpt_tiny, fault_plan=plan)
    prompts = [np.arange(1, 6, dtype=np.int32),
               np.arange(11, 16, dtype=np.int32)]
    bad = eng.submit(prompts[0], max_new_tokens=10)    # submit idx 0
    good = eng.submit(prompts[1], max_new_tokens=10)   # submit idx 1
    _drain(eng, clock)

    # BOTH streams complete bit-identically — the poison only ever hit
    # draft work, never the committed token path
    assert np.array_equal(bad.result(timeout=0), _ref(gpt_tiny,
                                                      prompts[0], 10))
    assert np.array_equal(good.result(timeout=0), _ref(gpt_tiny,
                                                       prompts[1], 10))

    snap = eng.metrics.snapshot()
    assert snap["spec_draft_quarantines"] == 1
    assert snap["spec_windows"] > 0          # request 1 kept speculating
    assert snap["completed"] == 2 and snap["failed"] == 0
    assert snap["quarantined"] == 0          # the REQUEST was never touched

    # the blame ladder is on the flight recorder, draft-scoped
    events = flight_recorder().snapshot()["events"]
    probes = [e for e in events if e["kind"] == "solo_probe"
              and e.get("stage") == "draft"]
    assert any(e["outcome"] == "failed" and e["submit_idx"] == 0
               for e in probes)
    quar = [e for e in events if e["kind"] == "draft_quarantine"]
    assert len(quar) == 1
    assert quar[0]["submit_idx"] == 0
    assert quar[0]["reason"] == "poisoned_draft"
    assert quar[0]["rid"] == bad.rid

    # exempt accounting: the target breaker never heard about any of it
    assert eng.supervisor.stats["exempt_failures"] >= 1
    assert eng.supervisor.stats["dispatch_failures"] == 0
    assert eng.supervisor.stats["quarantines"] == 0
    assert not eng.broken
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    assert eng.draft_pool.active_slots() == 0
    eng.stop()


@pytest.mark.fault_matrix
def test_unattributable_draft_failures_disable_spec_not_engine(gpt_tiny):
    """Draft dispatches that fail for EVERY solo probe are unattributable:
    they count a draft-only failstreak that disables speculation at
    breaker_threshold — the engine itself keeps serving plain decode,
    bit-identically, with the breaker closed."""
    from paddle_tpu import serving
    from paddle_tpu.obs.flight_recorder import flight_recorder
    from paddle_tpu.utils.fault_injection import FaultPlan

    flight_recorder().clear()
    # poison EVERY request's draft scope: the multi-row catch-up dispatch
    # fails AND both solo probes fail, so blame narrows to nobody
    # (len(blamed) == len(rows) > 1) — the textbook unattributable case
    plan = FaultPlan.from_spec(
        "poison_request@0:draft;poison_request@1:draft")
    clock = serving.SimClock()
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4,
                                breaker_threshold=2),
        clock=clock, draft_model=gpt_tiny, fault_plan=plan)
    prompts = [np.arange(1, 7, dtype=np.int32),
               np.arange(21, 27, dtype=np.int32)]
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
    _drain(eng, clock)
    for p, h in zip(prompts, handles):
        assert np.array_equal(h.result(timeout=0), _ref(gpt_tiny, p, 8))
    assert eng._spec_disabled and not eng.broken
    snap = eng.metrics.snapshot()
    assert snap["spec_windows"] == 0
    assert snap["spec_draft_quarantines"] == 0   # disabled, not blamed
    kinds = [e["kind"] for e in flight_recorder().snapshot()["events"]]
    assert kinds.count("draft_failure") == 2
    assert "draft_disabled" in kinds
    assert "draft_quarantine" not in kinds
    assert eng.supervisor.stats["dispatch_failures"] == 0
    assert eng.supervisor.stats["exempt_failures"] >= 2
    eng.pool.check_balance()
    eng.draft_pool.check_balance()
    eng.stop()


# ---- router failover mid-draft-window (ISSUE 14 x ISSUE 17) ----

@pytest.mark.fault_matrix
def test_router_failover_mid_draft_window_resumes_bit_identical(gpt_tiny):
    """Kill a spec-armed replica BETWEEN verify windows, with its draft
    pool run ahead of the committed stream: the router re-prefills every
    victim on the survivor from the handle's tokens — which only ever
    carry VERIFIED tokens, the engine never surfaces speculative state —
    so the resumed streams finish bit-identical to an uninterrupted
    one-shot generate()."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    clock = serving.SimClock()
    replicas = [
        serving.InProcessReplica(
            serving.LLMEngine(
                gpt_tiny,
                serving.LLMEngineConfig(num_slots=4, block_len=8,
                                        n_blocks=4, max_queue_depth=64),
                clock=clock, draft_model=gpt_tiny),
            i)
        for i in range(2)]
    router = serving.ReplicaRouter(replicas)
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 500, size=(6,)).astype(np.int32)
               for _ in range(4)]
    handles = [router.submit(p, max_new_tokens=14) for p in prompts]
    assert {h._replica.name for h in handles} == {"replica0", "replica1"}
    victims = [h for h in handles if h._replica is replicas[0]]

    for _ in range(2):       # prefill + one committed verify window
        clock.advance(0.01)
        router.pump()
    # the kill lands mid-stream AND mid-speculation: tokens are out, the
    # dead replica's draft pool has optimistically run ahead
    assert all(0 < len(h.tokens_so_far()) < 14 for h in handles)
    assert replicas[0].engine.spec_windows > 0

    set_global_plan(FaultPlan.from_spec("replica_crash@0"))
    steps = 0
    while router.has_work():
        clock.advance(0.01)
        router.pump()
        steps += 1
        assert steps < 2000

    from paddle_tpu.models.generation import generate
    ref = np.asarray(generate(gpt_tiny, np.stack(prompts),
                              max_new_tokens=14).numpy())[:, 6:]
    for i, h in enumerate(handles):
        assert np.array_equal(h.result(timeout=0), ref[i])
    assert all(h.failovers == 1 for h in victims)
    snap = router.metrics.snapshot()
    assert snap["resumed_streams"] == len(victims)
    assert snap["completed"] == 4 and snap["failed"] == 0
    # the survivor (also spec-armed) speculated through the resumed load
    assert replicas[1].engine.spec_windows > 0
    # the fleet healthz advertises per-replica accept rates iff a draft
    # is armed — the accept-rate runbook's fleet-level view
    rates = router.healthz()["spec_accept_rates"]
    assert rates["replica0"] is None          # crashed
    assert 0.0 <= rates["replica1"] <= 1.0
    replicas[1].engine.pool.check_balance()
    replicas[1].engine.draft_pool.check_balance()
