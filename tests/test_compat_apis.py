"""Root-namespace compat API tests: paddle.batch, paddle.reader decorators,
paddle.hub, paddle.linalg, paddle.callbacks, paddle.sysconfig (reference:
python/paddle/{batch,reader/decorator,hub,linalg,callbacks,sysconfig}.py).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_batch():
    r = paddle.batch(lambda: iter(range(10)), batch_size=3)
    got = [b for b in r()]
    assert got == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    r2 = paddle.batch(lambda: iter(range(10)), batch_size=3, drop_last=True)
    assert [b for b in r2()] == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]
    with pytest.raises(ValueError):
        paddle.batch(lambda: iter(()), batch_size=0)


def test_reader_decorators():
    from paddle_tpu import reader

    base = lambda: iter(range(8))  # noqa: E731
    assert list(reader.firstn(base, 3)()) == [0, 1, 2]
    assert list(reader.chain(base, base)()) == list(range(8)) * 2
    assert list(reader.buffered(base, 2)()) == list(range(8))
    assert sorted(reader.shuffle(base, 4)()) == list(range(8))
    assert list(reader.map_readers(lambda a, b: a + b, base, base)()) == \
        [2 * i for i in range(8)]
    assert list(reader.compose(base, base)()) == [(i, i) for i in range(8)]
    # cache: second pass replays without consuming the source again
    calls = []

    def tracked():
        calls.append(1)
        yield from range(3)

    c = reader.cache(tracked)
    assert list(c()) == [0, 1, 2]
    assert list(c()) == [0, 1, 2]
    assert len(calls) == 1
    got = sorted(reader.xmap_readers(lambda x: x * 10, base, 2, 4)())
    assert got == [i * 10 for i in range(8)]


def test_compose_misaligned_raises():
    from paddle_tpu import reader

    a = lambda: iter(range(3))  # noqa: E731
    b = lambda: iter(range(5))  # noqa: E731
    with pytest.raises(ValueError):
        list(reader.compose(a, b)())
    assert list(reader.compose(a, b, check_alignment=False)()) == \
        [(0, 0), (1, 1), (2, 2)]


def test_hub_local(tmp_path):
    hubconf = tmp_path / "hubconf.py"
    hubconf.write_text(
        "def lenet(num_classes=10):\n"
        "    \"\"\"A LeNet entrypoint.\"\"\"\n"
        "    import paddle_tpu as paddle\n"
        "    return paddle.vision.models.LeNet(num_classes=num_classes)\n")
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "lenet" in names
    assert "LeNet" in paddle.hub.help(str(tmp_path), "lenet", source="local")
    model = paddle.hub.load(str(tmp_path), "lenet", source="local",
                            num_classes=7)
    out = model(paddle.randn([1, 1, 28, 28]))
    assert tuple(out.shape) == (1, 7)
    with pytest.raises(RuntimeError):
        paddle.hub.list("user/repo", source="github")


def test_linalg_namespace():
    x = paddle.to_tensor(np.array([[4.0, 0.0], [0.0, 9.0]], np.float32))
    c = paddle.linalg.cholesky(x)
    np.testing.assert_allclose(np.asarray(c.data), [[2, 0], [0, 3]],
                               atol=1e-6)
    n = paddle.linalg.norm(paddle.to_tensor([3.0, 4.0]))
    assert float(n.item()) == pytest.approx(5.0)


def test_callbacks_namespace():
    assert paddle.callbacks.EarlyStopping is not None
    assert paddle.callbacks.ModelCheckpoint is not None


def test_sysconfig():
    assert isinstance(paddle.sysconfig.get_include(), str)
    assert isinstance(paddle.sysconfig.get_lib(), str)


def test_xmap_readers_error_propagates():
    from paddle_tpu import reader

    def boom(x):
        raise RuntimeError("mapper failed")

    with pytest.raises(RuntimeError):
        list(reader.xmap_readers(boom, lambda: iter(range(4)), 2, 4)())


def test_multiprocess_reader_none_samples_and_errors():
    from paddle_tpu import reader

    def src_with_none():
        yield 1
        yield None
        yield 2

    got = list(reader.multiprocess_reader([src_with_none])())
    assert got == [1, None, 2]

    def src_crash():
        yield 1
        raise IOError("disk gone")

    with pytest.raises(RuntimeError):
        list(reader.multiprocess_reader([src_crash])())


def test_categorical_log_prob_broadcast():
    import jax.numpy as jnp
    from paddle_tpu.distribution import Categorical
    logits = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    c = Categorical(paddle.to_tensor(logits))
    lp = np.asarray(c.log_prob(paddle.to_tensor([0, 1])).data)
    assert lp.shape == (3, 2)
    pr = np.asarray(c.probs(paddle.to_tensor([0, 1])).data)
    np.testing.assert_allclose(lp, np.log(pr), atol=1e-5)


def test_model_average_apply_before_step_is_noop():
    from paddle_tpu import nn
    from paddle_tpu.incubate import ModelAverage
    lin = nn.Linear(2, 2)
    w = lin.weight.numpy().copy()
    ma = ModelAverage(parameters=lin.parameters())
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(), w)


def test_root_tensor_ops():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert int(paddle.numel(x).item()) == 6
    assert int(paddle.rank(x).item()) == 2
    assert paddle.shape(x).numpy().tolist() == [2, 3]
    assert paddle.tolist(x) == [[0, 1, 2], [3, 4, 5]]
    np.testing.assert_allclose(
        np.asarray(paddle.diagonal(x).data), [0, 4])
    np.testing.assert_allclose(
        np.asarray(paddle.add_n([x, x, x]).data), 3 * np.asarray(x.data))
    np.testing.assert_allclose(
        np.asarray(paddle.mv(x, paddle.to_tensor(
            np.ones(3, np.float32))).data), [3.0, 12.0])
    m = paddle.to_tensor(np.eye(2, dtype=np.float32) * 4)
    np.testing.assert_allclose(np.asarray(paddle.inverse(m).data),
                               np.eye(2) * 0.25, atol=1e-6)
    si = paddle.shard_index(paddle.to_tensor(
        np.array([0, 5, 9], np.int64)), 10, 2, 0)
    assert np.asarray(si.data).tolist() == [0, -1, -1]
    si1 = paddle.shard_index(paddle.to_tensor(
        np.array([0, 5, 9], np.int64)), 10, 2, 1)
    assert np.asarray(si1.data).tolist() == [-1, 0, 4]
    # in-place variants mutate and return the same tensor
    y = paddle.to_tensor(np.ones((1, 3), np.float32))
    z = paddle.squeeze_(y)
    assert z is y and tuple(y.shape) == (3,)
    t = paddle.to_tensor(np.zeros(2, np.float32))
    assert paddle.tanh_(t) is t
    sc = paddle.to_tensor(np.zeros(4, np.float32))
    paddle.scatter_(sc, paddle.to_tensor(np.array([1], np.int64)),
                    paddle.to_tensor(np.array([[5.0]], np.float32).ravel()))
    assert np.asarray(sc.data)[1] == 5.0


def test_legacy_dataset_readers():
    from paddle_tpu import dataset

    # uci_housing: classic fit-a-line shapes
    sample = next(dataset.uci_housing.train()())
    assert sample[0].shape == (13,) and sample[1].shape == (1,)
    n_train = sum(1 for _ in dataset.uci_housing.train()())
    n_test = sum(1 for _ in dataset.uci_housing.test()())
    assert n_train == 404 and n_test == 102

    # mnist: flattened [-1,1] images through paddle.batch
    r = paddle.batch(dataset.mnist.train(), batch_size=4)
    imgs_labels = next(r())
    assert len(imgs_labels) == 4
    img, label = imgs_labels[0]
    assert img.shape == (784,) and -1.0 <= img.min() <= img.max() <= 1.0
    assert isinstance(label, int)

    # imdb: (sequence list, binary label)
    seq, lab = next(dataset.imdb.train()())
    assert isinstance(seq, list) and lab in (0, 1)

    # imikolov: n-gram tuples
    gram = next(dataset.imikolov.train(n=5)())
    assert len(gram) == 5

    # common.download refuses cleanly without cache
    with pytest.raises(RuntimeError):
        dataset.common.download("http://example.com/x.tgz", "x", "")


def test_mnist_reader_range_and_xmap_order_error():
    from paddle_tpu import dataset, reader

    img, _ = next(dataset.mnist.train()())
    assert img.min() < -0.5 and img.max() > 0.5  # real [-1,1] spread
    c, _ = next(dataset.cifar.train10()())
    assert c.max() > 0.1  # [0,1] images, not double-normalized

    # ordered xmap: results come back in order
    got = list(reader.xmap_readers(lambda x: x * 10,
                                   lambda: iter(range(8)), 3, 4,
                                   order=True)())
    assert got == [i * 10 for i in range(8)]

    # ordered xmap: a failing mapper raises instead of hanging
    def boom(x):
        if x == 2:
            raise RuntimeError("bad sample")
        return x

    with pytest.raises(RuntimeError):
        list(reader.xmap_readers(boom, lambda: iter(range(8)), 3, 4,
                                 order=True)())


def test_flops_lenet():
    m = paddle.vision.models.LeNet(num_classes=10)
    n = paddle.flops(m, input_size=(1, 1, 28, 28))
    # LeNet conv1: 6*28*28 out * (5*5*1) kernel = 117,600 MACs at least;
    # total for LeNet ~ 400k-500k MACs
    assert n > 100_000
    n2 = paddle.flops(m, input_size=(2, 1, 28, 28))
    assert n2 > n  # scales with batch
    # custom_ops: overriding a leaf class changes the count
    from paddle_tpu.nn import Linear
    n3 = paddle.flops(m, input_size=(1, 1, 28, 28),
                      custom_ops={Linear: lambda l, i, o: 0})
    assert n3 < n


def test_compose_dataset():
    from paddle_tpu.io import ComposeDataset, TensorDataset
    a = TensorDataset([paddle.to_tensor(np.arange(4, dtype=np.float32))])
    b = TensorDataset([paddle.to_tensor(np.arange(4, 8, dtype=np.float32))])
    ds = ComposeDataset([a, b])
    assert len(ds) == 4
    s = ds[1]
    assert float(np.asarray(s[0].data if hasattr(s[0], 'data') else s[0])) \
        == 1.0
    assert float(np.asarray(s[1].data if hasattr(s[1], 'data') else s[1])) \
        == 5.0


def test_vision_transform_extras():
    from paddle_tpu.vision import transforms as T
    img = (np.random.RandomState(0).rand(8, 8, 3) * 255).astype(np.uint8)

    gray = T.to_grayscale(img)
    assert gray.shape == (8, 8, 1)
    assert T.Grayscale(3)._apply_image(img).shape == (8, 8, 3)

    padded = T.pad(img, 2)
    assert padded.shape == (12, 12, 3)
    assert T.Pad([1, 0])._apply_image(img).shape == (8, 10, 3)

    c = T.crop(img, 2, 2, 4, 4)
    assert c.shape == (4, 4, 3)

    r = T.rotate(img, 90)
    assert r.shape == (8, 8, 3)
    # 90-degree rotation is exact under nearest sampling
    np.testing.assert_array_equal(T.rotate(T.rotate(img, 90), -90), img)
    assert T.rotate(img, 45, expand=True).shape[0] > 8

    bright = T.adjust_brightness(img, 2.0)
    assert bright.max() <= 255.0 and bright.mean() >= img.mean()
    T.adjust_contrast(img, 0.5)
    T.adjust_saturation(img, 0.5)
    h = T.adjust_hue(img, 0.25)
    assert h.shape == (8, 8, 3)
    # hue rotation preserves value channel (max of rgb)
    np.testing.assert_allclose(h.max(-1), img.astype(np.float32).max(-1),
                               atol=2.0)

    jit = T.ColorJitter(0.4, 0.4, 0.4, 0.2)
    assert jit._apply_image(img).shape == (8, 8, 3)
    rr = T.RandomRotation(30)._apply_image(img)
    assert rr.shape == (8, 8, 3)
    rc = T.RandomResizedCrop(4)._apply_image(img)
    assert rc.shape[:2] == (4, 4)
