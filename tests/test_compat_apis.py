"""Root-namespace compat API tests: paddle.batch, paddle.reader decorators,
paddle.hub, paddle.linalg, paddle.callbacks, paddle.sysconfig (reference:
python/paddle/{batch,reader/decorator,hub,linalg,callbacks,sysconfig}.py).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def test_batch():
    r = paddle.batch(lambda: iter(range(10)), batch_size=3)
    got = [b for b in r()]
    assert got == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    r2 = paddle.batch(lambda: iter(range(10)), batch_size=3, drop_last=True)
    assert [b for b in r2()] == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]
    with pytest.raises(ValueError):
        paddle.batch(lambda: iter(()), batch_size=0)


def test_reader_decorators():
    from paddle_tpu import reader

    base = lambda: iter(range(8))  # noqa: E731
    assert list(reader.firstn(base, 3)()) == [0, 1, 2]
    assert list(reader.chain(base, base)()) == list(range(8)) * 2
    assert list(reader.buffered(base, 2)()) == list(range(8))
    assert sorted(reader.shuffle(base, 4)()) == list(range(8))
    assert list(reader.map_readers(lambda a, b: a + b, base, base)()) == \
        [2 * i for i in range(8)]
    assert list(reader.compose(base, base)()) == [(i, i) for i in range(8)]
    # cache: second pass replays without consuming the source again
    calls = []

    def tracked():
        calls.append(1)
        yield from range(3)

    c = reader.cache(tracked)
    assert list(c()) == [0, 1, 2]
    assert list(c()) == [0, 1, 2]
    assert len(calls) == 1
    got = sorted(reader.xmap_readers(lambda x: x * 10, base, 2, 4)())
    assert got == [i * 10 for i in range(8)]


def test_compose_misaligned_raises():
    from paddle_tpu import reader

    a = lambda: iter(range(3))  # noqa: E731
    b = lambda: iter(range(5))  # noqa: E731
    with pytest.raises(ValueError):
        list(reader.compose(a, b)())
    assert list(reader.compose(a, b, check_alignment=False)()) == \
        [(0, 0), (1, 1), (2, 2)]


def test_hub_local(tmp_path):
    hubconf = tmp_path / "hubconf.py"
    hubconf.write_text(
        "def lenet(num_classes=10):\n"
        "    \"\"\"A LeNet entrypoint.\"\"\"\n"
        "    import paddle_tpu as paddle\n"
        "    return paddle.vision.models.LeNet(num_classes=num_classes)\n")
    names = paddle.hub.list(str(tmp_path), source="local")
    assert "lenet" in names
    assert "LeNet" in paddle.hub.help(str(tmp_path), "lenet", source="local")
    model = paddle.hub.load(str(tmp_path), "lenet", source="local",
                            num_classes=7)
    out = model(paddle.randn([1, 1, 28, 28]))
    assert tuple(out.shape) == (1, 7)
    with pytest.raises(RuntimeError):
        paddle.hub.list("user/repo", source="github")


def test_linalg_namespace():
    x = paddle.to_tensor(np.array([[4.0, 0.0], [0.0, 9.0]], np.float32))
    c = paddle.linalg.cholesky(x)
    np.testing.assert_allclose(np.asarray(c.data), [[2, 0], [0, 3]],
                               atol=1e-6)
    n = paddle.linalg.norm(paddle.to_tensor([3.0, 4.0]))
    assert float(n.item()) == pytest.approx(5.0)


def test_callbacks_namespace():
    assert paddle.callbacks.EarlyStopping is not None
    assert paddle.callbacks.ModelCheckpoint is not None


def test_sysconfig():
    assert isinstance(paddle.sysconfig.get_include(), str)
    assert isinstance(paddle.sysconfig.get_lib(), str)


def test_xmap_readers_error_propagates():
    from paddle_tpu import reader

    def boom(x):
        raise RuntimeError("mapper failed")

    with pytest.raises(RuntimeError):
        list(reader.xmap_readers(boom, lambda: iter(range(4)), 2, 4)())


def test_multiprocess_reader_none_samples_and_errors():
    from paddle_tpu import reader

    def src_with_none():
        yield 1
        yield None
        yield 2

    got = list(reader.multiprocess_reader([src_with_none])())
    assert got == [1, None, 2]

    def src_crash():
        yield 1
        raise IOError("disk gone")

    with pytest.raises(RuntimeError):
        list(reader.multiprocess_reader([src_crash])())


def test_categorical_log_prob_broadcast():
    import jax.numpy as jnp
    from paddle_tpu.distribution import Categorical
    logits = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    c = Categorical(paddle.to_tensor(logits))
    lp = np.asarray(c.log_prob(paddle.to_tensor([0, 1])).data)
    assert lp.shape == (3, 2)
    pr = np.asarray(c.probs(paddle.to_tensor([0, 1])).data)
    np.testing.assert_allclose(lp, np.log(pr), atol=1e-5)


def test_model_average_apply_before_step_is_noop():
    from paddle_tpu import nn
    from paddle_tpu.incubate import ModelAverage
    lin = nn.Linear(2, 2)
    w = lin.weight.numpy().copy()
    ma = ModelAverage(parameters=lin.parameters())
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(), w)
