"""Per-slot seeded sampling + grammar-constrained decoding in the
unified step (ISSUE 18).

The determinism contract under test: token `i` of a request's stream is
drawn from RNG lane `(request_seed, i)` — never from batch composition,
slot index, or wall clock — so a seeded sampled stream is bit-identical
across batch-mate changes, engine restart, and a mid-stream router
failover whose re-prefill restores the lane counter (`sample_offset`).
Grammar-constrained slots additionally never emit a token their
compiled token-DFA forbids, and speculative decoding composes with
sampling by drafting and verifying on the SAME lanes (seeded-replay
acceptance), keeping the output literally identical to plain sampled
decode.

Every scheduler test runs the PRODUCTION pump under a SimClock —
scripted instants, no sleeps, no thread flake."""
import json

import numpy as np
import pytest


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    from paddle_tpu.utils.fault_injection import set_global_plan
    set_global_plan(None)
    yield
    set_global_plan(None)


def _engine(model, clock, draft=None, **cfg_kw):
    from paddle_tpu import serving
    kw = dict(num_slots=2, block_len=8, n_blocks=8, max_queue_depth=64)
    kw.update(cfg_kw)
    return serving.LLMEngine(model, serving.LLMEngineConfig(**kw),
                             clock=clock, draft_model=draft)


def _drain(eng, clock, dt=0.01):
    steps = 0
    while eng.has_work():
        clock.advance(dt)
        eng.pump()
        steps += 1
        assert steps < 2000, "engine failed to converge"


def _params(**kw):
    from paddle_tpu.serving.llm.sampling import SamplingParams
    return SamplingParams(**kw)


_PROMPT = np.arange(1, 9, dtype=np.int32)

# nested-schema fixture: an object holding an integer, a nested object,
# and a boolean — every structural token the compiler supports
_TOKENS = {1: "{", 2: "}", 3: '"a"', 4: ":", 5: "1", 6: "23", 7: ",",
           8: '"b"', 9: "true", 10: "false", 11: '"o"', 12: '"x"'}
_SCHEMA = {
    "type": "object",
    "properties": {
        "a": {"type": "integer"},
        "o": {"type": "object",
              "properties": {"x": {"type": "boolean"}},
              "required": ["x"]},
        "b": {"type": "boolean"},
    },
    "required": ["a", "o", "b"],
}


def _grammar_params(seed=7):
    return _params(temperature=1.0, seed=seed,
                   grammar={"schema": _SCHEMA, "tokens": _TOKENS})


# ---- SamplingParams surface ----

def test_sampling_params_validation_and_payload():
    from paddle_tpu.serving.llm.sampling import SamplingParams
    for bad in (dict(temperature=0.0), dict(temperature=-1.0),
                dict(top_k=-1), dict(top_p=0.0), dict(top_p=1.5),
                dict(seed=-1), dict(seed=2 ** 31),
                dict(grammar={"schema": {}})):
        with pytest.raises(ValueError):
            SamplingParams(**bad).validate()
    # payload round trip: absent sampling fields -> None (pure greedy)
    assert SamplingParams.from_payload({"input_ids": [1, 2]}) is None
    sp = SamplingParams.from_payload(
        {"temperature": 0.8, "top_k": 40, "top_p": 0.9, "seed": 5})
    sp.validate()
    assert sp.do_sample and sp.seed == 5 and not sp.constrained


# ---- the seeding contract: bit-identity given (seed, params) ----

def test_seeded_bit_identity_across_batch_composition(gpt_tiny):
    """The same seeded request decoding ALONE and decoding beside a
    batch-mate (different slot, different step cadence) must emit the
    identical stream: the lane key is (seed, stream index), nothing
    else."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock)
    sp = _params(temperature=0.9, top_p=0.95, seed=42)
    h_solo = eng.submit(_PROMPT, max_new_tokens=10, sampling=sp)
    _drain(eng, clock)
    solo = h_solo.result(0)

    mate = eng.submit(np.arange(3, 12, dtype=np.int32), max_new_tokens=12)
    h_batched = eng.submit(_PROMPT, max_new_tokens=10, sampling=sp)
    _drain(eng, clock)
    mate.result(0)
    np.testing.assert_array_equal(solo, h_batched.result(0))
    # and a different seed actually changes the draw
    h_other = eng.submit(_PROMPT, max_new_tokens=10,
                         sampling=_params(temperature=0.9, top_p=0.95,
                                          seed=43))
    _drain(eng, clock)
    assert not np.array_equal(solo, h_other.result(0))


def test_seeded_bit_identity_across_engine_restart(gpt_tiny):
    from paddle_tpu import serving
    sp = _params(temperature=0.8, top_k=50, seed=99)
    streams = []
    for _ in range(2):      # two fresh engines = restart
        clock = serving.SimClock()
        eng = _engine(gpt_tiny, clock)
        h = eng.submit(_PROMPT, max_new_tokens=12, sampling=sp)
        _drain(eng, clock)
        streams.append(h.result(0))
    np.testing.assert_array_equal(streams[0], streams[1])


def test_sample_offset_resumes_mid_stream(gpt_tiny):
    """The failover re-prefill contract, exercised at the engine level:
    resubmitting prompt+emitted with sample_offset=len(emitted) makes
    the survivor's first draw use stream index len(emitted) — the
    suffix matches the uninterrupted run exactly."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock)
    sp = _params(temperature=0.9, top_p=0.9, seed=11)
    h_full = eng.submit(_PROMPT, max_new_tokens=12, sampling=sp)
    _drain(eng, clock)
    full = h_full.result(0)

    h_head = eng.submit(_PROMPT, max_new_tokens=4, sampling=sp)
    _drain(eng, clock)
    head = h_head.result(0)
    np.testing.assert_array_equal(head, full[:4])

    h_tail = eng.submit(np.concatenate([_PROMPT, head]), max_new_tokens=8,
                        sampling=sp, sample_offset=4)
    _drain(eng, clock)
    np.testing.assert_array_equal(h_tail.result(0), full[4:])


# ---- grammar-constrained decoding ----

def test_constrained_emits_only_grammar_valid_json(gpt_tiny):
    """Nested-schema fixture: every emitted token must be legal from the
    DFA state reached by its predecessors (checked token-by-token on the
    host against the compiled TokenDFA), and the finished stream must
    parse as JSON matching the schema's required keys — including the
    nested object."""
    from paddle_tpu import serving
    from paddle_tpu.serving.llm.sampling import compile_grammar
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock)
    h = eng.submit(_PROMPT, max_new_tokens=40, sampling=_grammar_params())
    _drain(eng, clock)
    toks = h.result(0)

    dfa = compile_grammar({"schema": _SCHEMA, "tokens": _TOKENS},
                          gpt_tiny.config.vocab_size, None)
    state = 0
    for t in toks:
        nxt = int(dfa.trans[state, int(t)])
        assert nxt >= 0, f"token {t} illegal from DFA state {state}"
        state = nxt
    assert bool(dfa.accept[state]), "stream ended in a non-accepting state"

    obj = json.loads("".join(_TOKENS[int(t)] for t in toks))
    assert set(obj) == {"a", "o", "b"}
    assert isinstance(obj["a"], int)
    assert isinstance(obj["o"], dict) and set(obj["o"]) == {"x"}
    assert isinstance(obj["b"], bool)


def test_constrained_replay_and_dfa_fast_forward(gpt_tiny):
    """Same seed -> same JSON; and a mid-object resume (sample_offset>0)
    fast-forwards the DFA through the emitted tail so the continuation
    is token-identical — the constrained half of the failover contract."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock)
    sp = _grammar_params(seed=21)
    h1 = eng.submit(_PROMPT, max_new_tokens=40, sampling=sp)
    _drain(eng, clock)
    full = h1.result(0)
    h2 = eng.submit(_PROMPT, max_new_tokens=40, sampling=sp)
    _drain(eng, clock)
    np.testing.assert_array_equal(full, h2.result(0))

    k = 3
    h3 = eng.submit(np.concatenate([_PROMPT, full[:k]]),
                    max_new_tokens=40 - k, sampling=sp, sample_offset=k)
    _drain(eng, clock)
    np.testing.assert_array_equal(h3.result(0), full[k:])

    # a resume tail that VIOLATES the grammar is rejected at submit
    from paddle_tpu.serving import RejectedError
    bad_tail = np.array([2, 2, 2], np.int32)    # "}}}" from the start
    with pytest.raises((ValueError, RejectedError)):
        eng.submit(np.concatenate([_PROMPT, bad_tail]),
                   max_new_tokens=8, sampling=sp, sample_offset=3)


def test_grammar_compile_rejections(gpt_tiny):
    """Free-form strings are out of the supported schema subset
    (ValueError), and a full grammar bank rejects the NEXT distinct
    grammar with reason=grammar_capacity instead of corrupting slots."""
    from paddle_tpu import serving
    from paddle_tpu.serving import RejectedError
    with pytest.raises(ValueError):
        from paddle_tpu.serving.llm.sampling import compile_grammar
        compile_grammar({"schema": {"type": "string"}, "tokens": _TOKENS},
                        512, None)

    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, max_grammars=1)
    h = eng.submit(_PROMPT, max_new_tokens=40, sampling=_grammar_params())
    other = {"type": "object", "properties": {"b": {"type": "boolean"}},
             "required": ["b"]}
    with pytest.raises(RejectedError) as ei:
        eng.submit(_PROMPT, max_new_tokens=8, sampling=_params(
            temperature=1.0, seed=1,
            grammar={"schema": other, "tokens": _TOKENS}))
    assert ei.value.reason == "grammar_capacity"
    _drain(eng, clock)
    h.result(0)


# ---- speculative decoding composes with sampling ----

def test_spec_sampled_stream_identical_to_plain_sampled(gpt_tiny):
    """Distribution-parity smoke, strengthened to exactness: because the
    draft proposes and the target verifies on the SAME (seed, index)
    lanes, rejection-sampled spec output is not merely unbiased — it is
    bit-identical to spec-off sampled decode, while still accepting
    drafts (the PR 17 speedup survives leaving greedy-land)."""
    from paddle_tpu import serving
    sp = _params(temperature=0.8, top_k=50, top_p=0.95, seed=99)

    clock = serving.SimClock()
    plain = _engine(gpt_tiny, clock)
    h = plain.submit(_PROMPT, max_new_tokens=16, sampling=sp)
    _drain(plain, clock)
    ref = h.result(0)

    clock2 = serving.SimClock()
    spec = _engine(gpt_tiny, clock2, draft=gpt_tiny)
    h2 = spec.submit(_PROMPT, max_new_tokens=16, sampling=sp)
    _drain(spec, clock2)
    np.testing.assert_array_equal(ref, h2.result(0))
    snap = spec.metrics.snapshot()
    assert snap["spec_accepted"] > 0, \
        "draft==target on shared lanes must accept proposals"
    assert snap["sampled_tokens"] == 16


def test_constrained_requests_never_speculate(gpt_tiny):
    """A grammar-constrained request on a spec-armed engine decodes
    WITHOUT draft windows (its mask depends on the in-step DFA state, so
    it takes exactly one emission per step), while an unconstrained
    batch-mate keeps speculating."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, draft=gpt_tiny, num_slots=2)
    h_con = eng.submit(_PROMPT, max_new_tokens=40,
                       sampling=_grammar_params())
    h_greedy = eng.submit(np.arange(2, 10, dtype=np.int32),
                          max_new_tokens=12)
    _drain(eng, clock)
    toks = h_con.result(0)
    h_greedy.result(0)
    snap = eng.metrics.snapshot()
    assert snap["spec_windows"] > 0          # the greedy mate speculated
    assert snap["constrained_tokens"] == toks.size
    # constrained stream is still grammar-clean next to speculation
    json.loads("".join(_TOKENS[int(t)] for t in toks))


# ---- router failover: the RNG-lane counter handoff ----

@pytest.mark.fault_matrix
def test_failover_mid_sampled_stream_token_identical(gpt_tiny):
    """Kill the hosting replica mid-sampled-stream: the survivor's
    re-prefill must restore the RNG-lane counter (sample_offset =
    harvested prefix length), making the resumed stream token-identical
    to the uninterrupted seeded run — the greedy failover bit-identity
    contract, extended to sampling."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    def fleet(clock):
        reps = [serving.InProcessReplica(
            _engine(gpt_tiny, clock, num_slots=4), i) for i in range(2)]
        return serving.ReplicaRouter(reps), reps

    def drive(router, clock):
        steps = 0
        while router.has_work():
            clock.advance(0.01)
            router.pump()
            steps += 1
            assert steps < 3000

    sp = _params(temperature=0.8, top_p=0.9, seed=1234)

    clock = serving.SimClock()
    router, _ = fleet(clock)
    h = router.submit(_PROMPT, max_new_tokens=14, sampling=sp)
    drive(router, clock)
    ref = h.result(0)

    clock = serving.SimClock()
    router, _ = fleet(clock)
    h = router.submit(_PROMPT, max_new_tokens=14, sampling=sp)
    for _ in range(6):              # decode far enough to be MID-stream
        clock.advance(0.01)
        router.pump()
    n_emitted = len(h.tokens_so_far())
    assert n_emitted > 0
    set_global_plan(FaultPlan.from_spec(
        f"replica_crash@{h._replica.index}"))
    drive(router, clock)
    assert h.failovers == 1
    np.testing.assert_array_equal(h.result(0), ref)


# ---- generate() jit cache: top-p keying + LRU churn bound ----

def test_generate_cache_keys_top_p_and_bounds_evictions(gpt_tiny):
    """top_p is part of the one-shot generate() jit-cache key (a
    distinct nucleus cutoff is a distinct compiled filter), and
    per-request param sweeps stay LRU-bounded: size never exceeds cap,
    evictions are counted, and a repeated key is a HIT."""
    from paddle_tpu.models.generation import generate
    from paddle_tpu.utils.jit_cache import JitLRUCache
    ids = np.arange(1, 5, dtype=np.int32)[None, :]
    # pin a tiny fresh cache so the sweep exercises eviction cheaply
    gpt_tiny.__dict__["_generate_jit_cache"] = JitLRUCache(
        2, name="generate")
    cache = gpt_tiny.__dict__["_generate_jit_cache"]
    try:
        out_a = generate(gpt_tiny, ids, max_new_tokens=2, do_sample=True,
                         temperature=0.9, top_p=0.9, seed=3)
        out_b = generate(gpt_tiny, ids, max_new_tokens=2, do_sample=True,
                         temperature=0.9, top_p=0.5, seed=3)
        assert cache.stats()["misses"] == 2     # top_p changed the key
        generate(gpt_tiny, ids, max_new_tokens=2, do_sample=True,
                 temperature=0.9, top_p=0.5, seed=3)
        assert cache.stats()["hits"] == 1       # repeat is a hit
        generate(gpt_tiny, ids, max_new_tokens=2, do_sample=True,
                 temperature=0.9, top_p=0.7, seed=3)
        st = cache.stats()
        assert st["size"] <= 2 and st["evictions"] == 1
        # determinism given the seed holds per compiled entry
        out_a2 = generate(gpt_tiny, ids, max_new_tokens=2, do_sample=True,
                          temperature=0.9, top_p=0.9, seed=3)
        np.testing.assert_array_equal(np.asarray(out_a.numpy()),
                                      np.asarray(out_a2.numpy()))
        assert np.asarray(out_b.numpy()).shape == (1, 6)
    finally:
        del gpt_tiny.__dict__["_generate_jit_cache"]


# ---- observability ----

def test_sampling_metrics_and_lane_export(gpt_tiny):
    """pdtpu_llm_sample_* families render; sampled/constrained token
    counters partition non-greedy traffic; the sample_mask ledger phase
    exists; and export_sampling_lanes serializes a live slot's lane
    (seed, next stream index, DFA state) mid-decode."""
    from paddle_tpu import serving
    from paddle_tpu.obs.serving_ledger import SERVING_LEDGER_PHASES
    assert "sample_mask" in SERVING_LEDGER_PHASES

    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock, economics=True)
    sp = _params(temperature=0.9, seed=5)
    h = eng.submit(_PROMPT, max_new_tokens=8, sampling=sp)
    for _ in range(4):
        clock.advance(0.01)
        eng.pump()
    n_now = len(h.tokens_so_far())
    assert n_now > 0 and eng.has_work()
    slot = next(iter(eng._active))
    lanes = eng.export_sampling_lanes([slot])
    assert lanes[slot]["seed"] == 5
    assert lanes[slot]["next_index"] == n_now
    assert lanes[slot]["grammar_key"] is None
    _drain(eng, clock)
    h.result(0)

    hc = eng.submit(_PROMPT, max_new_tokens=40, sampling=_grammar_params())
    _drain(eng, clock)
    n_con = hc.result(0).size

    snap = eng.metrics.snapshot()
    assert snap["sampled_tokens"] == 8
    assert snap["constrained_tokens"] == n_con
    assert snap["grammars_compiled"] == 1
    text = eng.metrics.render()
    for fam in ("pdtpu_llm_sample_slots", "pdtpu_llm_sample_tokens_total",
                "pdtpu_llm_sample_mask_overhead_ms",
                "pdtpu_llm_sample_grammars_compiled"):
        assert fam in text, fam
    led = eng.ledger.snapshot()
    assert "sample_mask" in led["phase_seconds"]
