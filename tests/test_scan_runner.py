"""Scan-fused multi-step runner (ISSUE 2 tentpole): ScanTrainStep must
produce the SAME training trajectory as K eager ShardedTrainStep calls —
including under gradient_merge (accum_k not dividing K) and AMP fp16
loss-scale overflow — while issuing N/K jitted dispatches for N steps.
Satellites ride along: ChunkPrefetcher semantics, chunk-aware
DeviceWorker/MultiTrainer/ResilientTrainer run loops, dtype-accurate
DataParallel grad bucketing, and the per-chunk throughput counters."""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.distributed import DistributedStrategy
from paddle_tpu.distributed.fleet.strategy_compiler import StrategyCompiler
from paddle_tpu.parallel import (ScanTrainStep, ShardedTrainStep,
                                 parallelize, stack_batches)

K = 4
N_STEPS = 8


def _mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _model_opt(lr=1e-2):
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(learning_rate=lr, parameters=model.parameters())
    return model, opt


def _batches(n=N_STEPS, scale=1.0, overflow_at=None):
    rng = np.random.RandomState(0)
    out = []
    for i in range(n):
        x = rng.randn(4, 8).astype(np.float32) * scale
        y = rng.randn(4, 4).astype(np.float32)
        if overflow_at is not None and i == overflow_at:
            x = x * 1e4  # fp16 range is ±65504: the mse loss overflows
        out.append((x, y))
    return out


def _mse(out, y):
    return nn.functional.mse_loss(out, y)


def _plan(mutate=None, opt=None, mesh=None):
    s = DistributedStrategy()
    if mutate is not None:
        mutate(s)
    return StrategyCompiler().compile(s, opt, mesh)


def _run_eager(batches, mutate=None):
    model, opt = _model_opt()
    mesh = _mesh()
    step = ShardedTrainStep(model, opt, mesh, loss_fn=_mse,
                            plan=_plan(mutate, opt, mesh))
    losses = [float(np.asarray(step(*b).data)) for b in batches]
    return losses, step


def _run_scan(batches, k=K, mutate=None):
    model, opt = _model_opt()
    mesh = _mesh()
    step = ScanTrainStep(model, opt, mesh, scan_steps=k, loss_fn=_mse,
                         plan=_plan(mutate, opt, mesh))
    losses = []
    for c in range(len(batches) // k):
        chunk = stack_batches(batches[c * k:(c + 1) * k])
        losses.extend(np.asarray(step(*chunk).data).tolist())
    return losses, step


def _assert_params_match(a, b):
    for key in a._params:
        np.testing.assert_allclose(
            np.asarray(a._params[key]), np.asarray(b._params[key]),
            rtol=1e-5, atol=1e-6, err_msg=key)


# ---- tentpole: scan/eager parity ----

def test_scan_eager_parity():
    batches = _batches()
    eager_losses, eager = _run_eager(batches)
    scan_losses, scan = _run_scan(batches)
    np.testing.assert_allclose(scan_losses, eager_losses,
                               rtol=1e-5, atol=1e-6)
    _assert_params_match(eager, scan)
    assert scan.dispatch_count == N_STEPS // K


def test_scan_parity_gradient_merge():
    # accum_k=3 does NOT divide K=4: merge boundaries (step % 3 == 0) land
    # mid-chunk, exercising the global-step threading through the scan
    def mutate(s):
        s.gradient_merge = True
        s.gradient_merge_configs = {"k_steps": 3}

    batches = _batches()
    eager_losses, eager = _run_eager(batches, mutate)
    scan_losses, scan = _run_scan(batches, mutate=mutate)
    np.testing.assert_allclose(scan_losses, eager_losses,
                               rtol=1e-5, atol=1e-6)
    _assert_params_match(eager, scan)


def test_scan_parity_amp_overflow():
    # an fp16 overflow mid-chunk (step 5 of 8, inside the 2nd chunk) must
    # shrink the loss scale and skip the update identically on both paths
    def mutate(s):
        s.amp = True
        s.amp_configs = {"dtype": "float16", "init_loss_scaling": 1024.0,
                         "decr_every_n_nan_or_inf": 1,
                         "use_dynamic_loss_scaling": True}

    batches = _batches(overflow_at=5)
    eager_losses, eager = _run_eager(batches, mutate)
    scan_losses, scan = _run_scan(batches, mutate=mutate)
    np.testing.assert_allclose(scan_losses, eager_losses,
                               rtol=1e-4, atol=1e-5)
    assert eager.loss_scale == scan.loss_scale
    assert scan.loss_scale < 1024.0  # the overflow actually shrank it
    _assert_params_match(eager, scan)


def test_scan_dispatch_count_32_steps():
    # acceptance: a 32-step run issues exactly 32/K jitted dispatches
    k = 8
    batches = _batches(32)
    model, opt = _model_opt()
    step = ScanTrainStep(model, opt, _mesh(), scan_steps=k, loss_fn=_mse)
    calls = []
    inner = step._chunk_jitted
    step._chunk_jitted = lambda *a, **kw: (calls.append(1) or inner(*a, **kw))
    for c in range(32 // k):
        step(*stack_batches(batches[c * k:(c + 1) * k]))
    assert len(calls) == 32 // k
    assert step.dispatch_count == 32 // k
    assert step._step_count == 32


def test_scan_rejects_unstacked_batch():
    model, opt = _model_opt()
    step = ScanTrainStep(model, opt, _mesh(), scan_steps=K, loss_fn=_mse)
    with pytest.raises(ValueError, match="stacked"):
        # a per-step [5, 8] batch, not a stacked [K=4, ...] chunk
        step(np.zeros((5, 8), np.float32), np.zeros((5, 4), np.float32))


def test_parallelize_routes_scan_steps():
    model, opt = _model_opt()
    s = DistributedStrategy()
    s.scan_steps = K
    step = parallelize(model, opt, mesh=_mesh(), strategy=s, loss_fn=_mse)
    assert isinstance(step, ScanTrainStep)
    assert step.scan_steps == K


def test_lr_vector_advances_scheduler():
    from types import SimpleNamespace
    from paddle_tpu.optimizer.lr import StepDecay
    sched = StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    model, _ = _model_opt()
    opt = optim.SGD(learning_rate=sched, parameters=model.parameters())
    vec = ScanTrainStep._lr_vector(SimpleNamespace(optimizer=opt), 4)
    np.testing.assert_allclose(vec, [0.1, 0.1, 0.05, 0.05])
    assert sched.last_epoch == 4  # runner owns the per-step advance


def test_stack_batches_shapes():
    cols = stack_batches(_batches(3))
    assert [c.shape for c in cols] == [(3, 4, 8), (3, 4, 4)]
    (single,) = stack_batches([np.zeros((2,)), np.ones((2,))])
    assert single.shape == (2, 2)
    with pytest.raises(ValueError):
        stack_batches([])


# ---- async double-buffered prefetcher ----

def test_prefetcher_matches_manual_stacking():
    from paddle_tpu.io import ChunkPrefetcher
    batches = _batches(8)
    pf = ChunkPrefetcher(batches, scan_steps=4, put_fn=lambda s: s)
    chunks = list(pf)
    assert len(chunks) == 2 and pf.dropped_steps == 0
    for c, chunk in enumerate(chunks):
        expect = stack_batches(batches[c * 4:(c + 1) * 4])
        for got, want in zip(chunk, expect):
            np.testing.assert_array_equal(np.asarray(got), want)


def test_prefetcher_drops_trailing_partial_chunk():
    from paddle_tpu.io import ChunkPrefetcher
    pf = ChunkPrefetcher(_batches(10), scan_steps=4, put_fn=lambda s: s)
    assert len(list(pf)) == 2
    assert pf.dropped_steps == 2  # accounted, not silent


def test_prefetcher_propagates_producer_error():
    from paddle_tpu.io import ChunkPrefetcher

    def bad_source():
        yield from _batches(4)
        raise ValueError("decode failed")

    pf = ChunkPrefetcher(bad_source(), scan_steps=4, put_fn=lambda s: s)
    it = iter(pf)
    next(it)
    with pytest.raises(ValueError, match="decode failed"):
        next(it)


def test_prefetcher_context_manager_drains_on_consumer_error():
    """ISSUE 3 satellite: a consumer that raises mid-epoch must not leak the
    producer thread or the staged (in-flight device_put) chunks — the
    context manager joins the thread and releases every pending chunk."""
    import threading
    from paddle_tpu.io import ChunkPrefetcher

    staged = []
    before = {t.ident for t in threading.enumerate()}
    with pytest.raises(RuntimeError, match="consumer blew up"):
        with ChunkPrefetcher(_batches(64), scan_steps=4, depth=2,
                             put_fn=lambda s: staged.append(s) or s) as pf:
            it = iter(pf)
            next(it)
            raise RuntimeError("consumer blew up")
    assert staged, "producer never ran"
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.name == "pdtpu-chunk-prefetch"]
    assert not leaked, "producer thread leaked past close()"
    assert pf._q.empty()          # staged chunks released, not pinned
    assert list(pf) == []         # closed: iterates as exhausted
    pf.close()                    # idempotent


def test_prefetcher_abandoned_consumer_producer_gives_up():
    """A consumer that walks away WITHOUT close() (no context manager) must
    not leave the producer busy-polling a full queue forever: after
    stall_timeout_s of no progress it drops the chunk and exits, releasing
    the staged buffers for the rest of the process lifetime."""
    import threading
    import time
    import warnings as _warnings
    from paddle_tpu.io import ChunkPrefetcher

    pf = ChunkPrefetcher(_batches(64), scan_steps=4, depth=1,
                         put_fn=lambda s: s, stall_timeout_s=0.3)
    it = iter(pf)
    next(it)                      # producer running, queue refills to full
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")   # the give-up warning fires on
        deadline = time.monotonic() + 10.0  # the producer thread
        while pf._thread.is_alive() and time.monotonic() < deadline:
            time.sleep(0.05)
    assert not pf._thread.is_alive(), \
        "producer still spinning after the consumer abandoned iteration"
    pf.close()                    # still safe after the give-up


# ---- chunk-aware trainer run loop ----

class _FakeScanStep:
    """scan_steps-shaped train fn: K steps per call, per-step loss vector."""

    scan_steps = 4

    def __init__(self):
        self.w = 0.0
        self.calls = []

    def __call__(self, chunk, *rest):
        start = int(np.asarray(chunk).reshape(-1)[0])
        self.calls.append(start)
        self.w += float(self.scan_steps)
        return np.array([1.0 / (start + i + 1)
                         for i in range(self.scan_steps)], np.float32)


def test_deviceworker_chunk_advances_k_steps(capsys):
    from paddle_tpu.distributed.trainer import DeviceWorker
    worker = DeviceWorker(_FakeScanStep(), print_period=2)
    worker.run_step(np.full((4,), 0.0, np.float32))
    assert worker.steps == 4
    worker.run_step(np.full((4,), 4.0, np.float32))
    assert worker.steps == 8
    tp = worker.throughput
    assert tp.total_steps == 8 and tp.steps_per_sec > 0


def test_multitrainer_prefetch_end_to_end():
    from paddle_tpu.distributed.trainer import MultiTrainer

    class _TwoArg(_FakeScanStep):
        def __call__(self, xs, ys):
            assert np.asarray(xs).shape[0] == self.scan_steps
            return super().__call__(np.zeros((1,)))

    trainer = MultiTrainer(_TwoArg(), print_period=0)
    trainer.train_from_dataset(_batches(9), prefetch=2)
    assert trainer.steps == 8  # 2 chunks of 4; the 9th batch dropped


def test_multitrainer_prefetch_requires_scan_fn():
    from paddle_tpu.distributed.trainer import MultiTrainer
    with pytest.raises(ValueError, match="scan-fused"):
        MultiTrainer(lambda b: 0.0).train_from_dataset(
            _batches(4), prefetch=2)


def test_chunk_tokens_counts_id_elements():
    from paddle_tpu.distributed.trainer import DeviceWorker
    args = (np.zeros((4, 2, 16), np.int32), np.zeros((4,), np.int32))
    assert DeviceWorker._chunk_tokens(args) == 4 * 2 * 16


# ---- resilient runtime at chunk granularity ----

def _resilient(tmp_path, fake, spec, **cfg):
    from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                  ResilientTrainer)
    from paddle_tpu.utils.fault_injection import FaultPlan
    return ResilientTrainer(
        fake, str(tmp_path / "ckpt"),
        get_state=lambda: {"w": fake.w},
        set_state=lambda s: setattr(fake, "w", s["w"]),
        config=ResilientConfig(**cfg),
        fault_plan=FaultPlan.from_spec(spec) if spec else None,
        use_orbax=False)


def test_resilient_nan_mid_chunk_rolls_back(tmp_path):
    # NaN at global step 5 = index 1 of the 2nd chunk [4..8): the sentinel
    # localizes it, and even under nan_policy='skip' the chunk rolls back —
    # the fused steps 6..7 already consumed the poisoned params
    fake = _FakeScanStep()
    t = _resilient(tmp_path, fake, "nan_loss@5",
                   nan_policy="skip", save_interval=1)
    summary = t.run(lambda i: np.full((4,), i, np.float32), num_steps=8)
    assert summary["completed_steps"] == 8
    assert summary["rollbacks"] == 1
    bad = [e for e in summary["events"] if e["kind"] == "bad_loss"]
    assert bad and bad[0]["step"] == 5 and bad[0]["chunk_start"] == 4
    rb = [e for e in summary["events"] if e["kind"] == "rollback"]
    assert rb and rb[0]["step"] == 4  # back to the chunk-boundary ckpt
    assert fake.calls == [0, 4, 4]    # chunk 2 replayed after rollback
    assert fake.w == 8.0              # restored state + clean replay


def test_resilient_chunk_nan_abort_policy(tmp_path):
    from paddle_tpu.distributed.resilient import UnrecoverableError
    fake = _FakeScanStep()
    t = _resilient(tmp_path, fake, "nan_loss@2", nan_policy="abort")
    with pytest.raises(UnrecoverableError, match="step 2"):
        t.run(lambda i: np.full((4,), i, np.float32), num_steps=8)


def test_resilient_chunk_requires_divisible_steps(tmp_path):
    fake = _FakeScanStep()
    t = _resilient(tmp_path, fake, "")
    with pytest.raises(ValueError, match="multiple"):
        t.run(lambda i: np.full((4,), i, np.float32), num_steps=6)


def test_resilient_chunk_save_cadence(tmp_path):
    # save_interval=3 with K=4: saves land at the first chunk boundary at or
    # past each interval multiple (4 covers 3, 8 covers 6 + end-of-run)
    fake = _FakeScanStep()
    t = _resilient(tmp_path, fake, "", save_interval=3)
    t.run(lambda i: np.full((4,), i, np.float32), num_steps=8)
    assert t.ckpt.latest_step() == 8
    assert t.ckpt.restore(4) is not None  # the mid-run boundary save


def test_corrupt_loss_vector_poisons_only_scheduled_step():
    from paddle_tpu.utils.fault_injection import FaultPlan
    plan = FaultPlan.from_spec("nan_loss@5;inf_loss@9")
    losses = np.ones((4,), np.float32)
    out = plan.corrupt_loss_vector(4, losses)       # steps 4..7
    assert np.isnan(out[1])
    assert np.isfinite([out[0], out[2], out[3]]).all()
    out2 = plan.corrupt_loss_vector(8, np.ones((4,), np.float32))
    assert np.isinf(out2[1])
    untouched = plan.corrupt_loss_vector(12, losses)
    assert untouched is losses  # nothing scheduled: no copy, no change


# ---- satellite: dtype-accurate grad bucketing ----

def test_bucket_grads_respects_dtype_itemsize():
    from paddle_tpu.distributed.data_parallel import _bucket_grads

    class _G:
        def __init__(self, arr):
            self.data = arr

    class _P:
        def __init__(self, arr):
            self.grad = _G(arr)

    n = 300_000  # fp16: 600KB/grad; fp32: 1.2MB/grad
    halves = [_P(np.zeros(n, np.float16)) for _ in range(4)]
    fulls = [_P(np.zeros(n, np.float32)) for _ in range(4)]
    # 1MB cap: two 600KB fp16 grads per bucket (the old hard-coded
    # 4-bytes/elem rule closed a bucket after ONE — 2x the configured MB)
    assert [len(b) for b in _bucket_grads(halves, 1)] == [2, 2]
    assert [len(b) for b in _bucket_grads(fulls, 1)] == [1, 1, 1, 1]


# ---- strategy wiring ----

def test_compiler_scan_steps_and_flag_fallback():
    import paddle_tpu.flags as flags
    plan = _plan(lambda s: setattr(s, "scan_steps", 4))
    assert plan.scan_steps == 4 and "scan" in plan.applied
    assert _plan().scan_steps == 1
    flags.set_flags({"FLAGS_scan_chunk": 8})
    try:
        assert _plan().scan_steps == 8  # env flag fills the default
        # an explicit strategy value wins over the flag
        assert _plan(lambda s: setattr(s, "scan_steps", 2)).scan_steps == 2
    finally:
        flags.set_flags({"FLAGS_scan_chunk": 0})


def test_compiler_scan_conflicts_disable_with_warning():
    def with_localsgd(s):
        s.scan_steps = 4
        s.localsgd = True
        s.localsgd_configs = {"k_steps": 2}

    with pytest.warns(UserWarning, match="does not compose"):
        plan = _plan(with_localsgd)
    assert plan.scan_steps == 1 and "scan" not in plan.applied

    def with_pipeline(s):
        s.scan_steps = 4
        s.pipeline = True

    with pytest.warns(UserWarning, match="does not compose"):
        plan = _plan(with_pipeline)
    assert plan.scan_steps == 1 and plan.pipeline


# ---- satellite: per-chunk throughput counters ----

def test_throughput_tracker_rates():
    from paddle_tpu.profiler import ThroughputTracker
    tp = ThroughputTracker(window=2)
    tp.update(steps=4, seconds=2.0, tokens=4000)
    assert tp.steps_per_sec == pytest.approx(2.0)
    assert tp.tokens_per_sec == pytest.approx(2000.0)
    tp.update(steps=4, seconds=1.0, tokens=4000)
    tp.update(steps=4, seconds=1.0, tokens=4000)  # first chunk ages out
    assert tp.steps_per_sec == pytest.approx(4.0)
    assert tp.total_steps == 12 and tp.total_tokens == 12000
    assert tp.summary()["total_seconds"] == pytest.approx(4.0)
