"""SelectedRows sparse-gradient tests (reference:
framework/selected_rows.h; lookup_table_grad is_sparse=True;
operators/optimizers/ sgd/adam sparse kernels).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core.selected_rows import SelectedRows


def _sparse_grad_from(emb, ids):
    out = emb(paddle.to_tensor(ids))
    loss = paddle.sum(out * out)
    loss.backward()
    return emb.weight.grad


def test_sparse_embedding_grad_is_selected_rows():
    emb = nn.Embedding(50, 8, sparse=True)
    ids = np.array([[1, 3], [3, 7]], np.int64)
    g = _sparse_grad_from(emb, ids)
    assert isinstance(g, SelectedRows)
    assert g.height == 50
    assert g.rows.shape == (4,)
    assert g.values.shape == (4, 8)
    # dense equivalence
    emb2 = nn.Embedding(50, 8, sparse=False)
    emb2.weight.set_value(emb.weight.numpy())
    g2 = _sparse_grad_from(emb2, ids)
    np.testing.assert_allclose(np.asarray(g.to_dense()),
                               np.asarray(g2.data), atol=1e-6)


def test_sparse_padding_idx_zero_grad():
    emb = nn.Embedding(20, 4, sparse=True, padding_idx=0)
    g = _sparse_grad_from(emb, np.array([[0, 5]], np.int64))
    dense = np.asarray(g.to_dense())
    assert np.abs(dense[0]).max() == 0.0
    assert np.abs(dense[5]).max() > 0.0


def test_sparse_sgd_matches_dense():
    ids = np.array([[2, 9, 2]], np.int64)

    def run(sparse):
        paddle.seed(0)
        emb = nn.Embedding(30, 4, sparse=sparse)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=emb.parameters())
        for _ in range(2):
            out = emb(paddle.to_tensor(ids))
            loss = paddle.sum(out * out)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return emb.weight.numpy()

    np.testing.assert_allclose(run(True), run(False), atol=1e-5)


def test_sparse_adam_lazy_matches_dense_on_touched_rows():
    ids = np.array([[4, 11]], np.int64)

    def run(sparse, lazy):
        paddle.seed(1)
        emb = nn.Embedding(30, 4, sparse=sparse)
        opt = optimizer.Adam(learning_rate=0.05, lazy_mode=lazy,
                             parameters=emb.parameters())
        for _ in range(3):
            out = emb(paddle.to_tensor(ids))
            loss = paddle.sum(out * out)
            loss.backward()
            opt.step()
            opt.clear_grad()
        return emb.weight.numpy()

    w_lazy = run(True, True)
    w_dense = run(False, False)
    # touched rows follow the same trajectory (untouched rows: lazy leaves
    # them alone, dense also leaves them alone since their grad/moments
    # stay 0 for adam with zero grads -> update = 0)
    np.testing.assert_allclose(w_lazy[[4, 11]], w_dense[[4, 11]], atol=1e-5)
    np.testing.assert_allclose(w_lazy[[0, 1, 29]], w_dense[[0, 1, 29]],
                               atol=1e-6)


def test_sparse_adam_nonlazy_densifies():
    emb = nn.Embedding(10, 4, sparse=True)
    opt = optimizer.Adam(learning_rate=0.1, parameters=emb.parameters())
    out = emb(paddle.to_tensor(np.array([[1]], np.int64)))
    paddle.sum(out).backward()
    assert isinstance(emb.weight.grad, SelectedRows)
    opt.step()  # falls back to the dense rule without error


def test_sparse_grad_accumulates_and_merges():
    emb = nn.Embedding(10, 4, sparse=True)
    out = emb(paddle.to_tensor(np.array([[1]], np.int64)))
    paddle.sum(out).backward()
    out = emb(paddle.to_tensor(np.array([[2]], np.int64)))
    paddle.sum(out).backward()
    g = emb.weight.grad
    assert isinstance(g, SelectedRows)
    assert g.rows.shape == (2,)
    dense = np.asarray(g.to_dense())
    assert np.abs(dense[1]).max() > 0 and np.abs(dense[2]).max() > 0


def test_sparse_with_global_clip_densifies():
    emb = nn.Embedding(10, 4, sparse=True)
    clip = paddle.nn.ClipGradByGlobalNorm(0.01)
    opt = optimizer.SGD(learning_rate=0.1, parameters=emb.parameters(),
                        grad_clip=clip)
    out = emb(paddle.to_tensor(np.array([[3]], np.int64)))
    paddle.sum(out * out).backward()
    w_before = emb.weight.numpy().copy()
    opt.step()
    delta = np.abs(emb.weight.numpy() - w_before)
    # clipped: total update norm bounded by lr * clip_norm
    assert 0 < delta.sum() <= 0.1 * 0.01 * 4 + 1e-6


# ---------------- dynamic-batch serving ----------------

def test_predictor_dynamic_batch(tmp_path):
    """The exported program is traced at one batch size; the predictor must
    serve smaller and larger batches (pad / chunk) with identical values
    (analysis_predictor dynamic feed parity)."""
    from paddle_tpu.inference import Config, create_predictor, export_model

    paddle.seed(0)
    m = nn.Linear(6, 3)
    x8 = paddle.randn([8, 6])
    prefix = str(tmp_path / "lin")
    export_model(m, [x8], prefix)
    pred = create_predictor(Config(prefix))

    rng = np.random.RandomState(0)
    for bs in (8, 3, 20):
        xin = rng.randn(bs, 6).astype(np.float32)
        (out,) = pred.run([xin])
        want = np.asarray(m(paddle.to_tensor(xin)).data)
        assert out.shape == (bs, 3)
        np.testing.assert_allclose(out, want, atol=1e-5)


def test_sparse_grad_with_gradscaler():
    """amp.GradScaler must unscale SelectedRows grads (values only)."""
    from paddle_tpu import amp
    emb = nn.Embedding(10, 4, sparse=True)
    opt = optimizer.SGD(learning_rate=0.1, parameters=emb.parameters())
    scaler = amp.GradScaler(init_loss_scaling=2.0)
    out = emb(paddle.to_tensor(np.array([[3]], np.int64)))
    loss = paddle.sum(out * out)
    w_before = emb.weight.numpy().copy()
    scaler.scale(loss).backward()
    g_scaled = emb.weight.grad
    assert isinstance(g_scaled, SelectedRows)
    vals_scaled = np.asarray(g_scaled.values).copy()
    scaler.step(opt)
    scaler.update()
    assert not scaler._found_inf
    # applied update = -lr * (scaled values / loss_scale) on row 3 only
    delta = emb.weight.numpy() - w_before
    np.testing.assert_allclose(delta[3], -0.1 * vals_scaled[0] / 2.0,
                               atol=1e-6)
    mask = np.ones(10, bool)
    mask[3] = False
    assert np.abs(delta[mask]).max() == 0.0
