"""Native C++ data feed: build, roundtrip, multithreaded completeness."""
import os

import numpy as np
import pytest

from paddle_tpu.io.native_feed import (NativeRecordReader, RecordFileDataset,
                                       write_record_file)


def test_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    records = [b"hello", b"", b"world" * 100, np.arange(10).tobytes()]
    assert write_record_file(path, records) == 4
    reader = NativeRecordReader([path], num_threads=1)
    out = list(reader)
    reader.close()
    assert out == records


def test_multithreaded_reads_all_records(tmp_path):
    files = []
    expected = set()
    for i in range(6):
        path = str(tmp_path / f"f{i}.rec")
        recs = [f"file{i}-rec{j}".encode() for j in range(50)]
        write_record_file(path, recs)
        expected.update(recs)
        files.append(path)
    reader = NativeRecordReader(files, num_threads=4, capacity=32)
    got = list(reader)
    reader.close()
    assert len(got) == 300
    assert set(got) == expected


def test_repeat_epochs(tmp_path):
    path = str(tmp_path / "r.rec")
    write_record_file(path, [b"x", b"y"])
    reader = NativeRecordReader([path], num_threads=1, repeat=3)
    got = list(reader)
    reader.close()
    assert len(got) == 6


def test_record_dataset_with_decoder(tmp_path):
    path = str(tmp_path / "d.rec")
    rows = [np.random.RandomState(i).randn(8).astype(np.float32)
            for i in range(20)]
    write_record_file(path, [r.tobytes() for r in rows])
    ds = RecordFileDataset([path],
                           decoder=lambda b: np.frombuffer(b, np.float32))
    out = list(ds)
    assert len(out) == 20
    np.testing.assert_allclose(out[0], rows[0])

    from paddle_tpu.io import DataLoader
    loader = DataLoader(ds, batch_size=5)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0].shape == [5, 8]


def test_large_record_grows_buffer(tmp_path):
    path = str(tmp_path / "big.rec")
    big = os.urandom(3 << 20)  # 3MB > default 1MB buffer
    write_record_file(path, [big])
    reader = NativeRecordReader([path], num_threads=1)
    out = list(reader)
    reader.close()
    assert out == [big]


def test_cpp_datafeed_unit_tests():
    """Build and run the colocated C++ unit test (reference *_test.cc +
    paddle_gtest_main.cc analog, csrc/datafeed/datafeed_test.cc)."""
    import subprocess
    import os
    d = os.path.join(os.path.dirname(__file__), "..", "csrc", "datafeed")
    r = subprocess.run(["make", "test"], cwd=d, capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL PASSED" in r.stdout
