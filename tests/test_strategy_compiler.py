"""DistributedStrategy flags must transform the executed step, not decorate it.

Mirrors the reference's meta-optimizer tests (test_fleet_*_meta_optimizer.py),
which assert on the REWRITTEN program; here the assertions target the jaxpr /
compiled HLO of the sharded train step and the step's observable behavior.
"""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import DistributedStrategy
from paddle_tpu.distributed.fleet.strategy_compiler import (CompiledStrategy,
                                                            StrategyCompiler)
from paddle_tpu.parallel import LocalSGDTrainStep, ShardedTrainStep, parallelize


def _mesh(data=1, sharding=1, model=1):
    devs = np.array(jax.devices()[:data * sharding * model]).reshape(
        data, 1, sharding, model)
    return Mesh(devs, ("data", "pipe", "sharding", "model"))


class TinyMLP(nn.Layer):
    def __init__(self, d=8):
        super().__init__()
        self.fc1 = nn.Linear(d, d)
        self.fc2 = nn.Linear(d, d)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _mse(out, y):
    return nn.functional.mse_loss(out, y)


def _step_for(strategy, mesh=None, lr=0.1, d=8):
    paddle.seed(0)
    model = TinyMLP(d)
    opt = optimizer.SGD(learning_rate=lr, parameters=model.parameters())
    mesh = mesh or _mesh(data=2)
    return parallelize(model, opt, mesh=mesh, strategy=strategy,
                       loss_fn=_mse), model


def _abstract_args(step):
    lr = jnp.float32(0.1)
    st = jnp.int32(1)
    rng = jax.random.PRNGKey(0)
    x = jnp.ones((4, 8), jnp.float32)
    y = jnp.zeros((4, 8), jnp.float32)
    return (step._params, step._opt_state, step._buffers, step._extras, lr,
            st, rng, (x, y))


# ---- compiler plan ----

def test_transform_order_matches_reference_ranking():
    s = DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 4}
    s.recompute = True
    s.amp = True
    plan = StrategyCompiler().compile(s)
    assert plan.applied == ["amp", "recompute", "gradient_merge"]
    assert plan.describe() == "amp -> recompute -> gradient_merge"


def test_lars_swaps_momentum_optimizer():
    s = DistributedStrategy()
    s.lars = True
    m = TinyMLP()
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.8,
                             parameters=m.parameters())
    plan = StrategyCompiler().compile(s, opt)
    from paddle_tpu.optimizer.optimizer import LarsMomentum
    assert isinstance(plan.optimizer, LarsMomentum)
    assert plan.optimizer._momentum == 0.8


def test_lamb_swaps_adam_optimizer():
    s = DistributedStrategy()
    s.lamb = True
    s.lamb_configs = {"lamb_weight_decay": 0.05}
    m = TinyMLP()
    opt = optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
    plan = StrategyCompiler().compile(s, opt)
    from paddle_tpu.optimizer.optimizer import Lamb
    assert isinstance(plan.optimizer, Lamb)


def test_localsgd_conflicts_with_sharding():
    s = DistributedStrategy()
    s.localsgd = True
    s.sharding = True
    s.sharding_configs = {"stage": 1}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan = StrategyCompiler().compile(s)
    assert plan.localsgd_k == 0
    assert "localsgd" not in plan.applied
    assert any("localsgd" in str(x.message) for x in w)


def test_fleet_distributed_optimizer_applies_lamb():
    from paddle_tpu.distributed import fleet
    s = DistributedStrategy()
    s.lamb = True
    fleet.init(is_collective=True, strategy=s)
    m = TinyMLP()
    opt = optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
    wrapped = fleet.distributed_optimizer(opt, strategy=s)
    from paddle_tpu.optimizer.optimizer import Lamb
    inner = getattr(wrapped, "_inner_opt", wrapped)
    assert isinstance(inner, Lamb)


# ---- flags change the compiled step ----

def test_recompute_inserts_remat_in_jaxpr():
    s = DistributedStrategy()
    s.recompute = True
    step, _ = _step_for(s)
    jaxpr = jax.make_jaxpr(step._train_step_fn)(*_abstract_args(step))
    assert "remat" in str(jaxpr)
    s2 = DistributedStrategy()
    step2, _ = _step_for(s2)
    jaxpr2 = jax.make_jaxpr(step2._train_step_fn)(*_abstract_args(step2))
    assert "remat" not in str(jaxpr2)


def test_amp_strategy_traces_bf16_matmuls():
    s = DistributedStrategy()
    s.amp = True  # dtype defaults to bfloat16
    step, _ = _step_for(s)
    jaxpr = str(jax.make_jaxpr(step._train_step_fn)(*_abstract_args(step)))
    assert "bf16" in jaxpr
    s2 = DistributedStrategy()
    step2, _ = _step_for(s2)
    jaxpr2 = str(jax.make_jaxpr(step2._train_step_fn)(*_abstract_args(step2)))
    assert "bf16" not in jaxpr2


def test_gradient_merge_applies_every_k_steps():
    s = DistributedStrategy()
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2, "avg": True}
    step, model = _step_for(s)
    w0 = np.asarray(step._params["fc1.weight"])
    x = paddle.randn([8, 8])
    y = paddle.randn([8, 8])
    step(x, y)  # banks grads, must NOT touch params
    w1 = np.asarray(step._params["fc1.weight"])
    np.testing.assert_allclose(w1, w0)
    acc = np.asarray(step._extras["accum"]["fc1.weight"])
    assert np.abs(acc).max() > 0
    step(x, y)  # k-th step applies
    w2 = np.asarray(step._params["fc1.weight"])
    assert np.abs(w2 - w0).max() > 0
    acc2 = np.asarray(step._extras["accum"]["fc1.weight"])
    np.testing.assert_allclose(acc2, np.zeros_like(acc2), atol=1e-7)


def test_gradient_merge_parity_with_plain_step():
    # k=2 over the same batch twice == one plain step on that batch (avg=True)
    sm = DistributedStrategy()
    sm.gradient_merge = True
    sm.gradient_merge_configs = {"k_steps": 2, "avg": True}
    merged, _ = _step_for(sm)
    plain, _ = _step_for(DistributedStrategy())
    x = paddle.randn([8, 8])
    y = paddle.randn([8, 8])
    merged(x, y)
    merged(x, y)
    plain(x, y)
    np.testing.assert_allclose(np.asarray(merged._params["fc1.weight"]),
                               np.asarray(plain._params["fc1.weight"]),
                               rtol=1e-5, atol=1e-6)


def test_fp16_scaler_state_skips_on_overflow():
    s = DistributedStrategy()
    s.amp = True
    s.amp_configs = {"dtype": "float16", "init_loss_scaling": 2.0 ** 15,
                     "decr_every_n_nan_or_inf": 1, "decr_ratio": 0.5}
    step, _ = _step_for(s)
    w0 = np.asarray(step._params["fc1.weight"])
    x = paddle.randn([8, 8])
    # y ~ 100 makes scaled f16 cotangents overflow at scale 2^15: the first
    # steps must be skipped with the scale halving each time
    y = paddle.randn([8, 8]) * 100.0
    step(x, y)
    np.testing.assert_allclose(np.asarray(step._params["fc1.weight"]), w0)
    assert step.loss_scale < 2.0 ** 15
    # recovery: once scale * grad fits in f16, updates resume
    for _ in range(10):
        step(x, y)
    assert np.abs(np.asarray(step._params["fc1.weight"]) - w0).max() > 0
    assert step.loss_scale < 2.0 ** 15


def test_stage2_shards_gradients_distinct_from_stage1():
    # ZeRO-2: grads land in the sharded layout (on TPU the partitioner lowers
    # the cross-replica reduction + slice to reduce-scatter; the CPU backend
    # splits it as all-reduce + slice) and the updated params are re-gathered.
    s = DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": 2, "min_shard_numel": 0}
    mesh = _mesh(data=2, sharding=2)
    step, _ = _step_for(s, mesh=mesh)
    assert step.zero_stage == 2
    # grads carry the sharding axis, distinct from stage-1 (param layout)
    assert any("sharding" in str(sp) for sp in step.grad_specs.values())
    hlo = step._jitted.lower(*_abstract_args(step)).compile().as_text()
    assert "all-gather" in hlo  # sharded updates -> replicated params
    # stage 1: grads stay in param layout, no param re-gather needed
    s1 = DistributedStrategy()
    s1.sharding = True
    s1.sharding_configs = {"stage": 1, "min_shard_numel": 0}
    step1, _ = _step_for(s1, mesh=mesh)
    assert all("sharding" not in str(sp) for sp in step1.grad_specs.values())
    hlo1 = step1._jitted.lower(*_abstract_args(step1)).compile().as_text()
    assert "all-gather" not in hlo1


def test_stage2_loss_parity_with_stage0():
    mesh = _mesh(data=2, sharding=2)
    s2 = DistributedStrategy()
    s2.sharding = True
    s2.sharding_configs = {"stage": 2, "min_shard_numel": 0}
    sharded, _ = _step_for(s2, mesh=mesh)
    plain, _ = _step_for(DistributedStrategy(), mesh=mesh)
    x = paddle.randn([8, 8])
    y = paddle.randn([8, 8])
    for _ in range(3):
        l2 = sharded(x, y)
        l0 = plain(x, y)
    np.testing.assert_allclose(float(l2.item()), float(l0.item()), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(sharded._params["fc1.weight"]),
                               np.asarray(plain._params["fc1.weight"]),
                               rtol=1e-4, atol=1e-5)


def test_zero_offload_keeps_opt_state_on_host():
    s = DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": 1, "offload": True, "min_shard_numel": 0}
    mesh = _mesh(data=2, sharding=2)
    paddle.seed(0)
    model = TinyMLP()
    opt = optimizer.Adam(learning_rate=0.1, parameters=model.parameters())
    step = parallelize(model, opt, mesh=mesh, strategy=s, loss_fn=_mse)
    assert step._offload
    kinds = {a.sharding.memory_kind
             for slots in step._opt_state.values() for a in slots.values()}
    assert kinds == {"pinned_host"}
    x = paddle.randn([8, 8])
    y = paddle.randn([8, 8])
    l1 = step(x, y)
    # state returns to host after the step; numerics match the on-device run
    kinds = {a.sharding.memory_kind
             for slots in step._opt_state.values() for a in slots.values()}
    assert kinds == {"pinned_host"}
    s2 = DistributedStrategy()
    s2.sharding = True
    s2.sharding_configs = {"stage": 1, "offload": False, "min_shard_numel": 0}
    paddle.seed(0)
    model2 = TinyMLP()
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=model2.parameters())
    plain = parallelize(model2, opt2, mesh=mesh, strategy=s2, loss_fn=_mse)
    l2 = plain(x, y)
    np.testing.assert_allclose(float(l1.item()), float(l2.item()), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(step._params["fc1.weight"]),
                               np.asarray(plain._params["fc1.weight"]),
                               rtol=1e-5, atol=1e-6)


def test_zero_spec_skips_tiny_tensors_and_stacks_axes():
    from paddle_tpu.parallel.api import _zero_spec
    mesh = _mesh(data=2, sharding=2)
    # tiny layernorm vector stays replicated (the GSPMD full-remat fix)
    assert _zero_spec(P(), (128,), mesh) == P()
    # large matrix gets the sharding axis
    assert _zero_spec(P(), (1024, 1024), mesh) == P("sharding", None)
    # idempotent: an already-extended spec is not extended again
    assert _zero_spec(P("sharding", None), (1024, 1024), mesh) == \
        P("sharding", None)
    # already-sharded dim is extended in place (vocab-parallel embedding):
    # grads arrive sharded on that dim, so the ZeRO reshard stays local
    mesh2 = _mesh(data=2, sharding=2, model=2)
    spec = _zero_spec(P("model", None), (512, 128), mesh2)
    assert spec == P(("model", "sharding"), None)


def test_localsgd_diverges_then_syncs():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 3, "begin_step": 1}
    mesh = _mesh(data=4)
    step, _ = _step_for(s, mesh=mesh, lr=0.5)
    assert isinstance(step, LocalSGDTrainStep)
    x = paddle.randn([16, 8])
    y = paddle.randn([16, 8])
    step(x, y)               # step 1 <= begin_step: synced
    assert step.param_spread() < 1e-6
    step(x, y)               # step 2: local only — ranks diverge
    assert step.param_spread() > 1e-6
    step(x, y)               # step 3 % 3 == 0: averaged again
    assert step.param_spread() < 1e-6


def test_localsgd_k1_matches_plain_dp():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 1, "begin_step": 0}
    mesh = _mesh(data=2)
    local, _ = _step_for(s, mesh=mesh)
    plain, _ = _step_for(DistributedStrategy(), mesh=_mesh(data=2))
    x = paddle.randn([8, 8])
    y = paddle.randn([8, 8])
    l1 = local(x, y)
    l2 = plain(x, y)
    np.testing.assert_allclose(float(l1.item()), float(l2.item()), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(local._params["fc1.weight"])[0],
        np.asarray(plain._params["fc1.weight"]), rtol=1e-4, atol=1e-5)
