"""Zero silent dead flags (VERDICT r4 weak 4 / item 3).

Every public DistributedStrategy field must fall in exactly one bucket —
consumed by the strategy compiler, consumed by another subsystem, absorbed by
XLA/JAX by design, or GPU-only (warns when set) — and the newly wired flags
(fp16_allreduce, adaptive_localsgd, recompute_configs.checkpoints,
gradient_scale_configs, sync_batch_norm, asp, qat) must observably change the
compiled step. Reference anchors: fp16_allreduce_optimizer.py:148,
localsgd_optimizer.py:197 (AdaptiveLocalSGD), distributed_strategy.proto:26
(RecomputeConfig), asp_optimizer.py, qat meta-optimizer."""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import DistributedStrategy
from paddle_tpu.distributed.fleet import strategy_compiler as sc
from paddle_tpu.parallel import parallelize
from paddle_tpu.parallel.localsgd import LocalSGDTrainStep


def _mesh(data=1, sharding=1, model=1):
    devs = np.array(jax.devices()[:data * sharding * model]).reshape(
        data, 1, sharding, model)
    return Mesh(devs, ("data", "pipe", "sharding", "model"))


class TinyMLP(nn.Layer):
    def __init__(self, d=8):
        super().__init__()
        self.fc1 = nn.Linear(d, d)
        self.fc2 = nn.Linear(d, d)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _mse(out, y):
    return nn.functional.mse_loss(out, y)


def _step_for(strategy, mesh=None, lr=0.1, d=8, opt_cls=optimizer.SGD):
    paddle.seed(0)
    model = TinyMLP(d)
    opt = opt_cls(learning_rate=lr, parameters=model.parameters())
    mesh = mesh or _mesh(data=2)
    return parallelize(model, opt, mesh=mesh, strategy=strategy,
                       loss_fn=_mse), model


def _step_jaxpr(step):
    lr = jnp.float32(0.1)
    st = jnp.int32(1)
    rng = jax.random.PRNGKey(0)
    x = jnp.ones((4, 8), jnp.float32)
    y = jnp.zeros((4, 8), jnp.float32)
    return str(jax.make_jaxpr(step._train_step_fn)(
        step._params, step._opt_state, step._buffers, step._extras, lr, st,
        rng, (x, y)))


def _data(seed=0, b=4, d=8):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(b, d), jnp.float32),
            jnp.asarray(r.randn(b, d), jnp.float32))


# ---- the exhaustive field audit: no field can silently rot ----

def test_every_public_field_is_classified():
    s = DistributedStrategy()
    public = {k for k in s.__dict__ if not k.startswith("__")}
    buckets = [set(sc.CONSUMED_HERE), set(sc.CONSUMED_ELSEWHERE),
               set(sc.ABSORBED), set(sc.GPU_ONLY)]
    classified = set().union(*buckets)
    unclassified = public - classified
    assert not unclassified, (
        f"DistributedStrategy fields with no declared consumer: "
        f"{sorted(unclassified)} — wire them or add them to a "
        "strategy_compiler bucket with a justification")
    for i, a in enumerate(buckets):
        for b in buckets[i + 1:]:
            assert not (a & b), f"field in two buckets: {a & b}"
    # buckets must not reference fields that no longer exist (stale docs)
    ghost = classified - public
    assert not ghost, f"classified but nonexistent fields: {sorted(ghost)}"


def test_gpu_only_defaults_match_strategy_defaults():
    s = DistributedStrategy()
    for knob, default in sc.GPU_ONLY.items():
        assert getattr(s, knob) == default, knob


def test_gpu_only_knob_warns_when_set():
    s = DistributedStrategy()
    s.nccl_comm_num = 4
    with pytest.warns(UserWarning, match="nccl_comm_num.*no TPU analog"):
        sc.StrategyCompiler().compile(s)


def test_semi_auto_warns_gspmd_owns_it():
    s = DistributedStrategy()
    s.semi_auto = True
    with pytest.warns(UserWarning, match="GSPMD"):
        sc.StrategyCompiler().compile(s)


# ---- fp16_allreduce (fp16_allreduce_optimizer.py:148) ----

def test_fp16_allreduce_casts_grads_in_step():
    s = DistributedStrategy()
    s.fp16_allreduce = True
    step, _ = _step_for(s)
    assert "fp16_allreduce" in step.plan.applied
    assert "f16" in _step_jaxpr(step)
    x, y = _data()
    assert np.isfinite(float(step(x, y).item()))


def test_fp16_allreduce_quantizes_but_tracks_fp32_training():
    x, y = _data()
    s0 = DistributedStrategy()
    step0, _ = _step_for(s0)
    s1 = DistributedStrategy()
    s1.fp16_allreduce = True
    step1, _ = _step_for(s1)
    l0 = [float(step0(x, y).item()) for _ in range(3)]
    l1 = [float(step1(x, y).item()) for _ in range(3)]
    # fp16-quantized grads: close to, but not bit-identical with, fp32
    np.testing.assert_allclose(l1, l0, rtol=5e-3, atol=5e-3)


# ---- gradient_scale_configs ----

def test_gradient_scale_sum_scales_update_by_dp():
    x, y = _data()
    s_avg = DistributedStrategy()
    step_a, model_a = _step_for(s_avg)
    s_sum = DistributedStrategy()
    s_sum.gradient_scale_configs = {"scale_strategy": "sum"}
    step_s, model_s = _step_for(s_sum)
    p0 = {k: np.asarray(v) for k, v in step_a._params.items()}
    step_a(x, y)
    step_s(x, y)
    for k in p0:
        da = np.asarray(step_a._params[k]) - p0[k]
        ds = np.asarray(step_s._params[k]) - p0[k]
        if np.abs(da).max() < 1e-9:
            continue
        # SGD update is linear in the grad: sum = avg * n_batch_shards (2)
        np.testing.assert_allclose(ds, da * 2.0, rtol=1e-5, atol=1e-7)


def test_gradient_scale_customized_raises():
    s = DistributedStrategy()
    s.gradient_scale_configs = {"scale_strategy": "customized"}
    with pytest.raises(ValueError, match="scale_strategy"):
        sc.StrategyCompiler().compile(s)


# ---- selective recompute (recompute_configs.checkpoints) ----

def test_selective_recompute_inserts_remat_and_keeps_numerics():
    x, y = _data()
    plain = DistributedStrategy()
    step0, _ = _step_for(plain)
    losses0 = [float(step0(x, y).item()) for _ in range(3)]

    s = DistributedStrategy()
    s.recompute = True
    s.recompute_configs = {"checkpoints": ["fc1"]}
    step1, model = _step_for(s)
    assert "recompute" in step1.plan.applied
    assert step1.plan.recompute_checkpoints == ["fc1"]
    assert getattr(model.fc1.forward, "_is_remat_wrapped", False)
    assert not getattr(model.fc2.forward, "_is_remat_wrapped", False)
    jx = _step_jaxpr(step1)
    assert "remat" in jx  # the checkpointed sublayer shows up as remat2
    losses1 = [float(step1(x, y).item()) for _ in range(3)]
    # remat recomputes, never changes math
    np.testing.assert_allclose(losses1, losses0, rtol=1e-6, atol=1e-6)


def test_selective_recompute_no_match_warns_and_falls_back():
    s = DistributedStrategy()
    s.recompute = True
    s.recompute_configs = {"checkpoints": ["nonexistent_layer"]}
    with pytest.warns(UserWarning, match="matched no sublayer"):
        step, _ = _step_for(s)
    assert "remat" in _step_jaxpr(step)  # whole-loss fallback


# ---- asp routed through the strategy ----

def test_asp_strategy_prunes_and_keeps_sparsity():
    from paddle_tpu.incubate.asp import check_sparsity
    s = DistributedStrategy()
    s.asp = True
    step, model = _step_for(s, lr=0.5)
    assert "asp" in step.plan.applied
    x, y = _data()
    for i in range(3):
        step(x, y)
    for k, arr in step._params.items():
        if k.endswith("weight"):
            assert check_sparsity(np.asarray(arr)), f"{k} lost 2:4 sparsity"


# ---- qat routed through the strategy ----

def test_qat_strategy_swaps_layers():
    from paddle_tpu.quantization import QuantedLayer
    s = DistributedStrategy()
    s.qat = True
    step, model = _step_for(s)
    assert "qat" in step.plan.applied
    assert isinstance(model.fc1, QuantedLayer)
    assert isinstance(model.fc2, QuantedLayer)
    x, y = _data()
    assert np.isfinite(float(step(x, y).item()))


# ---- sync_batch_norm routed through the strategy ----

def test_sync_batch_norm_strategy_converts_model():
    from paddle_tpu.nn.layer.norm import SyncBatchNorm

    class BNNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)
            self.bn = nn.BatchNorm1D(8)

        def forward(self, x):
            return self.bn(self.fc(x))

    paddle.seed(0)
    model = BNNet()
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    s = DistributedStrategy()
    s.sync_batch_norm = True
    step = parallelize(model, opt, mesh=_mesh(data=2), strategy=s,
                       loss_fn=_mse)
    assert isinstance(step.model.bn, SyncBatchNorm)
    x, y = _data()
    assert np.isfinite(float(step(x, y).item()))


# ---- adaptive localsgd (localsgd_optimizer.py:197) ----

def test_adaptive_localsgd_routes_and_adapts_k():
    s = DistributedStrategy()
    s.adaptive_localsgd = True
    s.adaptive_localsgd_configs = {"init_k_steps": 2, "begin_step": 2}
    paddle.seed(0)
    model = TinyMLP()
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("data",))
    step = parallelize(model, opt, mesh=mesh, strategy=s, loss_fn=_mse)
    assert isinstance(step, LocalSGDTrainStep) and step.adaptive
    x, y = _data(b=8)
    losses = [float(step(x, y).item()) for _ in range(8)]
    assert all(np.isfinite(losses))
    # k is live state, adapted at sync points, clipped to [1, 16]
    assert 1 <= step.current_k_steps <= 16
    assert int(step._extras["last_step"]) >= 1
    # loss_0/lr_0 captured at step 1
    assert float(step._extras["loss_0"]) == pytest.approx(losses[0], rel=1e-5)


def test_plain_localsgd_still_static_k():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 3}
    paddle.seed(0)
    model = TinyMLP()
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    step = parallelize(model, opt, mesh=mesh, strategy=s, loss_fn=_mse)
    assert isinstance(step, LocalSGDTrainStep) and not step.adaptive
    assert step.current_k_steps == 3
    x, y = _data(b=8)
    for _ in range(3):
        assert np.isfinite(float(step(x, y).item()))


# ---- per-execution-path consumption (no flag may die on a sub-path) ----

def test_fp16_allreduce_reaches_pipeline_collectives():
    from paddle_tpu.models.llama import LlamaForCausalLM
    from paddle_tpu.parallel.pipeline import PipelinedTrainStep
    paddle.seed(0)
    m = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "pipe"))
    step = PipelinedTrainStep(m, opt, mesh, n_micro=2,
                              fp16_allreduce_dtype="float16")
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 512, (4, 16)), jnp.int32)
    # the cast must be IN the compiled step, before the grad collectives
    txt = step._jitted.lower(
        step._stacked, step._rest, step._opt_state, step._extras,
        jnp.float32(1e-3), jnp.int32(1), (ids, ids)).as_text()
    assert "f16" in txt
    assert np.isfinite(float(step(ids, ids).item()))


def test_gradient_scale_sum_reaches_pipeline():
    from paddle_tpu.models.llama import LlamaForCausalLM
    from paddle_tpu.parallel.pipeline import PipelinedTrainStep
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 512, (4, 16)), jnp.int32)

    def build(gs):
        paddle.seed(0)
        m = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
        opt = optimizer.SGD(learning_rate=1e-3, parameters=m.parameters())
        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        return PipelinedTrainStep(m, opt, Mesh(devs, ("data", "pipe")),
                                  n_micro=2, grad_scale=gs)

    sa, ss = build("avg"), build("sum")
    sa(ids, ids)
    ss(ids, ids)
    # SGD update linear in grad: sum-scaled update = avg update * dp(2);
    # compare on a param that actually moved
    paddle.seed(0)
    m0 = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    init = {k: np.asarray(v.numpy(), np.float64)
            for k, v in m0.named_parameters()}
    checked = 0
    for ka in sa._rest:
        pa = np.asarray(sa._rest[ka], np.float64)
        ps = np.asarray(ss._rest[ka], np.float64)
        da, ds = pa - init[ka], ps - init[ka]
        if np.abs(da).max() < 1e-9:
            continue
        # compare on elements big enough that fp32 update rounding (single
        # ulps on tiny deltas) cannot dominate the ratio
        big = np.abs(da) > 0.05 * np.abs(da).max()
        np.testing.assert_allclose(ds[big], da[big] * 2.0, rtol=2e-2,
                                   atol=1e-7)
        checked += 1
    assert checked, "no rest param moved; test is vacuous"


def test_asp_with_pipeline_fails_loud():
    s = DistributedStrategy()
    s.asp = True
    s.pipeline = True
    with pytest.raises(ValueError, match="asp does not compose"):
        sc.StrategyCompiler().compile(s)


def test_localsgd_drops_fp16_allreduce_with_warning():
    s = DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 2}
    s.fp16_allreduce = True
    with pytest.warns(UserWarning, match="fp16_allreduce"):
        plan = sc.StrategyCompiler().compile(s)
    assert plan.fp16_allreduce_dtype is None
    assert "fp16_allreduce" not in plan.applied


# ---- fp16 compression on the explicit collective path ----

def test_sync_gradients_fn_fp16_compression():
    from paddle_tpu.distributed.data_parallel import sync_gradients_fn
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    sync16 = sync_gradients_fn("data", comm_dtype="float16")
    sync32 = sync_gradients_fn("data")

    def run(sync):
        def f(g):
            return sync({"w": g})["w"]
        from jax.sharding import PartitionSpec as P
        m = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("data"),
                                  out_specs=P("data")))
        g = jnp.stack([jnp.full((4,), 1.0001, jnp.float32),
                       jnp.full((4,), 3.0001, jnp.float32)])
        return np.asarray(m(g))

    out16, out32 = run(sync16), run(sync32)
    # both average to ~2.0001; the fp16 path quantizes (not equal bitwise)
    np.testing.assert_allclose(out16, 2.0, atol=1e-2)
    np.testing.assert_allclose(out32, 2.0001, atol=1e-5)
    assert not np.array_equal(out16, out32)
    # and the jaxpr really casts before the psum
    from jax.sharding import PartitionSpec as P

    def f16(g):
        return sync16({"w": g})["w"]
    jx = str(jax.make_jaxpr(jax.shard_map(
        f16, mesh=mesh, in_specs=P("data"), out_specs=P("data")))(
        jnp.ones((2, 4), jnp.float32)))
    assert "f16" in jx


def test_selective_recompute_direct_step_construction():
    """A directly-built ShardedTrainStep (no parallelize) with
    recompute_checkpoints must still remat — never silently drop it."""
    from paddle_tpu.parallel import ShardedTrainStep
    s = DistributedStrategy()
    s.recompute = True
    s.recompute_configs = {"checkpoints": ["fc1"]}
    plan = sc.StrategyCompiler().compile(s)
    paddle.seed(0)
    model = TinyMLP()
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = ShardedTrainStep(model, opt, _mesh(data=2), loss_fn=_mse,
                            plan=plan)
    assert getattr(model.fc1.forward, "_is_remat_wrapped", False)
    assert "remat" in _step_jaxpr(step)
    x, y = _data()
    assert np.isfinite(float(step(x, y).item()))
