"""Ragged paged attention + chunked prefill suite (ISSUE 7).

Parity: `ragged_paged_attention` (ops/paged_attention.py) against the
fp32 `_attention_reference` oracle at <= 1e-5, over ragged lengths
(1, block_len-1, block_len, multi-block), mixed prefill-chunk + decode
rows, fragmented vs defragged block tables, bf16 inputs, and the real
Pallas kernel in interpret mode on CPU. Plus the satellite units — the
shared JitLRUCache policy, the pool's version-gated device block
tables / fragmentation gauge — and the engine-level acceptance
scenarios: chunk-granular poison blame (co-scheduled decode rows
survive bit-identically) and the SimClock TTFT win over the retired
pow2-bucket prefill.
"""
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


# ---- kernel parity vs the fp32 reference oracle ----

def _rand(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32),
                       dtype)


def _ref_paged(q, k_cache, v_cache, table, seq_lens, q_pos, block_len,
               pages_per_row, scale=None):
    """Oracle: gather each row's pages into contiguous KV, then run
    `_attention_reference` in fp32 with the ragged causal+length mask
    (col <= q_pos+t AND col < seq_len) as an additive mask."""
    from paddle_tpu.ops.attention import _NEG_INF, _attention_reference
    B, H, Tq, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    table = np.asarray(table)
    n_blocks = table.shape[1]
    Sk = n_blocks * block_len
    outs = []
    for b in range(B):
        ks, vs = [], []
        for j in range(n_blocks):
            g = max(int(table[b, j]), 0)
            r, p = divmod(g, pages_per_row)
            ks.append(k_cache[r, :, p * block_len:(p + 1) * block_len, :])
            vs.append(v_cache[r, :, p * block_len:(p + 1) * block_len, :])
        kb = jnp.concatenate(ks, axis=1)[None]     # [1, Hkv, Sk, D]
        vb = jnp.concatenate(vs, axis=1)[None]
        if kb.shape[1] != H:
            rep = H // kb.shape[1]
            kb = jnp.repeat(kb, rep, axis=1)
            vb = jnp.repeat(vb, rep, axis=1)
        col = np.arange(Sk)
        row = int(q_pos[b]) + np.arange(Tq)[:, None]
        keep = (col[None, :] <= row) & (col[None, :] < int(seq_lens[b]))
        mask = jnp.asarray(np.where(keep, 0.0, _NEG_INF),
                           jnp.float32)[None]
        outs.append(_attention_reference(
            q[b:b + 1].astype(jnp.float32), kb.astype(jnp.float32),
            vb.astype(jnp.float32), causal=False, scale=scale, mask=mask))
    return jnp.concatenate(outs, 0)


def _identity_table(batch, n_blocks):
    return (np.arange(batch, dtype=np.int32)[:, None] * n_blocks
            + np.arange(n_blocks, dtype=np.int32)[None, :])


def test_scan_parity_ragged_decode_lengths():
    """Decode-shaped rows (Tq=1) at every ragged length class: 1,
    block_len-1, block_len, and multi-block — plus GQA head repeat."""
    from paddle_tpu.ops.paged_attention import ragged_paged_attention
    rng = np.random.RandomState(0)
    B, H, Hkv, D, bl, nb = 4, 4, 2, 16, 8, 4
    k = _rand(rng, (B, Hkv, nb * bl, D))
    v = _rand(rng, (B, Hkv, nb * bl, D))
    lens = np.array([1, bl - 1, bl, 3 * bl + 3], np.int32)
    q = _rand(rng, (B, H, 1, D))
    table = _identity_table(B, nb)
    q_pos = lens - 1                       # the newest token's position
    out = ragged_paged_attention(q, k, v, table, lens, q_pos,
                                 block_len=bl, impl="scan")
    ref = _ref_paged(q, k, v, table, lens, q_pos, bl, nb)
    assert float(jnp.max(jnp.abs(out - ref))) <= 1e-5


def test_scan_parity_mixed_prefill_decode_rows():
    """One dispatch, four row flavors: chunk-0 prefill, chunk-1 prefill,
    a 1-valid-token decode row, and a near-capacity decode row. Only each
    row's valid query slice (t < adv) is compared — trailing chunk
    padding is garbage by contract."""
    from paddle_tpu.ops.paged_attention import ragged_paged_attention
    rng = np.random.RandomState(1)
    B, H, Hkv, D, bl, nb, C = 4, 4, 4, 16, 8, 4, 8
    k = _rand(rng, (B, Hkv, nb * bl, D))
    v = _rand(rng, (B, Hkv, nb * bl, D))
    q = _rand(rng, (B, H, C, D))
    q_pos = np.array([0, 8, 13, 29], np.int32)
    adv = np.array([8, 8, 1, 1], np.int32)
    lens = (q_pos + adv).astype(np.int32)
    table = _identity_table(B, nb)
    out = ragged_paged_attention(q, k, v, table, lens, q_pos,
                                 block_len=bl, impl="scan")
    ref = _ref_paged(q, k, v, table, lens, q_pos, bl, nb)
    for b in range(B):
        n = int(adv[b])
        diff = jnp.max(jnp.abs(out[b, :, :n] - ref[b, :, :n]))
        assert float(diff) <= 1e-5, f"row {b}"


def test_fragmented_table_matches_defragged_layout():
    """The same logical KV served through a scattered page layout must
    produce bitwise the result of the contiguous (defragged) layout: the
    block table is pure indirection, never arithmetic."""
    from paddle_tpu.ops.paged_attention import ragged_paged_attention
    rng = np.random.RandomState(2)
    H, Hkv, D, bl = 2, 2, 8, 4
    n_logical = 3
    kv_len = n_logical * bl
    k_log = _rand(rng, (1, Hkv, kv_len, D))
    v_log = _rand(rng, (1, Hkv, kv_len, D))
    q = _rand(rng, (1, H, 5, D))
    lens = np.array([10], np.int32)
    q_pos = np.array([5], np.int32)

    # defragged: one slab row, identity pages [0, 1, 2] (+1 pad block)
    k_a = jnp.pad(k_log, ((0, 0), (0, 0), (0, bl), (0, 0)))
    table_a = np.array([[0, 1, 2, -1]], np.int32)
    out_a = ragged_paged_attention(q, k_a, jnp.pad(
        v_log, ((0, 0), (0, 0), (0, bl), (0, 0))), table_a, lens, q_pos,
        block_len=bl, impl="scan")

    # fragmented: 2 slab rows (8 pages), logical block j lives at page
    # perm[j], the rest of the slab is noise the table never names
    perm = [5, 2, 7]
    k_b = _rand(rng, (2, Hkv, 4 * bl, D))
    v_b = _rand(rng, (2, Hkv, 4 * bl, D))
    for j, g in enumerate(perm):
        r, p = divmod(g, 4)
        sl = slice(p * bl, (p + 1) * bl)
        k_b = k_b.at[r, :, sl].set(k_log[0, :, j * bl:(j + 1) * bl])
        v_b = v_b.at[r, :, sl].set(v_log[0, :, j * bl:(j + 1) * bl])
    table_b = np.array([perm + [-1]], np.int32)
    out_b = ragged_paged_attention(q, k_b, v_b, table_b, lens, q_pos,
                                   block_len=bl, pages_per_row=4,
                                   impl="scan")
    assert np.array_equal(np.asarray(out_a), np.asarray(out_b))


def test_bf16_parity_documented_tolerance():
    from paddle_tpu.ops.paged_attention import ragged_paged_attention
    rng = np.random.RandomState(3)
    B, H, Hkv, D, bl, nb = 2, 2, 2, 16, 8, 3
    k32 = _rand(rng, (B, Hkv, nb * bl, D))
    v32 = _rand(rng, (B, Hkv, nb * bl, D))
    q32 = _rand(rng, (B, H, 4, D))
    lens = np.array([20, 7], np.int32)
    q_pos = np.array([16, 3], np.int32)
    table = _identity_table(B, nb)
    out = ragged_paged_attention(
        q32.astype(jnp.bfloat16), k32.astype(jnp.bfloat16),
        v32.astype(jnp.bfloat16), table, lens, q_pos, block_len=bl,
        impl="scan")
    ref = _ref_paged(q32, k32, v32, table, lens, q_pos, bl, nb)
    assert out.dtype == jnp.bfloat16
    assert float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref))) <= 2e-2


def test_pallas_interpret_matches_scan_and_reference():
    """The REAL kernel body (grid, scalar-prefetched index maps, VMEM
    online-softmax scratch) runs on CPU via interpret=True and must agree
    with the scan path and the oracle — tier-1 proof that the TPU kernel
    computes the same function."""
    from paddle_tpu.ops.paged_attention import (_HAS_PALLAS,
                                                ragged_paged_attention)
    if not _HAS_PALLAS:
        pytest.skip("pallas unavailable in this environment")
    rng = np.random.RandomState(4)
    B, H, Hkv, D, bl, nb = 2, 2, 1, 8, 4, 3
    k = _rand(rng, (B, Hkv, nb * bl, D))
    v = _rand(rng, (B, Hkv, nb * bl, D))
    q = _rand(rng, (B, H, 4, D))
    lens = np.array([9, 5], np.int32)
    q_pos = np.array([5, 4], np.int32)
    table = _identity_table(B, nb)
    scan = ragged_paged_attention(q, k, v, table, lens, q_pos,
                                  block_len=bl, impl="scan")
    pal = ragged_paged_attention(q, k, v, table, lens, q_pos,
                                 block_len=bl, impl="pallas_interpret")
    assert float(jnp.max(jnp.abs(pal - scan))) <= 1e-6
    ref = _ref_paged(q, k, v, table, lens, q_pos, bl, nb)
    for b in range(B):
        n = int(lens[b] - q_pos[b])        # valid query rows
        assert float(jnp.max(jnp.abs(pal[b, :, :n] - ref[b, :, :n]))) \
            <= 1e-5


def test_chunked_prefill_bitwise_equals_whole_prompt():
    """Chunk invariance, the property the engine's bit-identity rests on:
    at a fixed block_len, a query row's output depends only on its
    absolute position and the committed KV — never on the chunk boundary
    — so chunked outputs match the whole-prompt dispatch BITWISE."""
    from paddle_tpu.ops.paged_attention import ragged_paged_attention
    rng = np.random.RandomState(5)
    H, Hkv, D, bl, nb, L = 2, 2, 8, 8, 3, 20
    k = _rand(rng, (1, Hkv, nb * bl, D))
    v = _rand(rng, (1, Hkv, nb * bl, D))
    q = _rand(rng, (1, H, L, D))
    table = _identity_table(1, nb)
    whole = ragged_paged_attention(
        q, k, v, table, np.array([L], np.int32), np.array([0], np.int32),
        block_len=bl, impl="scan")
    C = 8
    for off in range(0, L, C):
        n = min(C, L - off)
        qc = jnp.zeros((1, H, C, D), q.dtype).at[:, :, :n].set(
            q[:, :, off:off + n])
        out = ragged_paged_attention(
            qc, k, v, table, np.array([off + n], np.int32),
            np.array([off], np.int32), block_len=bl, impl="scan")
        assert np.array_equal(np.asarray(out[:, :, :n]),
                              np.asarray(whole[:, :, off:off + n])), \
            f"chunk at offset {off} diverged from whole-prompt prefill"


# ---- JitLRUCache: the one shared executable-cache policy ----

def test_jit_lru_caches_hits_and_evicts_oldest():
    from paddle_tpu.utils.jit_cache import JitLRUCache
    built = []
    c = JitLRUCache(cap=2, name="t")
    for key in ("a", "b", "a", "c"):       # 'a' refreshed before 'c' lands
        c.get_or_build(key, lambda k=key: built.append(k) or k.upper())
    assert built == ["a", "b", "c"]        # hit on the second 'a'
    assert "b" not in c and "a" in c and "c" in c   # LRU evicted 'b'
    assert len(c) == 2
    assert c.stats() == {"size": 2, "cap": 2, "hits": 1, "misses": 3,
                         "evictions": 1}
    assert c.get_or_build("a", lambda: "REBUILT") == "A"


def test_jit_lru_churn_warning(caplog):
    from paddle_tpu.utils.jit_cache import JitLRUCache
    c = JitLRUCache(cap=1, name="churny", churn_window=4)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.jit_cache"):
        for i in range(4):                 # every build evicts: 100% churn
            c.get_or_build(i, lambda i=i: i)
    assert any("churny jit cache churning" in r.message
               for r in caplog.records)
    assert c.evictions == 3


def test_jit_lru_rejects_senseless_cap():
    from paddle_tpu.utils.jit_cache import JitLRUCache
    with pytest.raises(ValueError, match="cap"):
        JitLRUCache(cap=0)


def test_generate_uses_shared_lru_cache(gpt_tiny):
    from paddle_tpu.models.generation import generate
    from paddle_tpu.utils.jit_cache import JitLRUCache
    generate(gpt_tiny, np.array([[1, 2, 3]], dtype=np.int32),
             max_new_tokens=2)
    cache = gpt_tiny.__dict__["_generate_jit_cache"]
    assert isinstance(cache, JitLRUCache)
    assert cache.stats()["size"] >= 1
    generate(gpt_tiny, np.array([[1, 2, 3]], dtype=np.int32),
             max_new_tokens=2)             # same shapes: pure cache hit
    assert cache.hits >= 1


# ---- pool device mirrors (block table / seq_lens / fragmentation) ----

def _pool(num_slots=2, block_len=4, n_blocks=3, pad_tokens=0):
    from paddle_tpu.serving.llm import SlotPagedKVPool

    def init_cache(batch, max_len, **kw):
        return [(jnp.zeros((batch, 1, max_len, 4)),
                 jnp.zeros((batch, 1, max_len, 4)))]

    return SlotPagedKVPool(init_cache, num_slots=num_slots,
                           block_len=block_len, n_blocks=n_blocks,
                           pad_tokens=pad_tokens)


def test_device_block_table_identity_and_version_gating():
    p = _pool(num_slots=2, n_blocks=3)
    t1 = p.device_block_table()
    assert np.array_equal(np.asarray(t1), [[0, 1, 2], [3, 4, 5]])
    assert p.device_block_table() is t1    # no change -> no re-upload
    p.set_block_row(0, [4, 2])             # incremental row update
    t2 = p.device_block_table()
    assert t2 is not t1
    assert np.array_equal(np.asarray(t2)[0], [4, 2, 0])
    p.set_block_row(0, [4, 2])             # identical row: version steady
    assert p.device_block_table() is t2
    with pytest.raises(ValueError, match="at most"):
        p.set_block_row(1, [0, 1, 2, 3])


def test_device_seq_lens_upload_only_on_change():
    p = _pool()
    s = p.allocate(8)
    l1 = p.device_seq_lens()
    assert p.device_seq_lens() is l1
    p.set_length(s, 5)
    l2 = p.device_seq_lens()
    assert l2 is not l1 and int(np.asarray(l2)[s]) == 5
    p.set_length(s, 5)                     # no-op write: no re-upload
    assert p.device_seq_lens() is l2
    p.free(s)                              # length 5 -> 0 is a change
    assert p.device_seq_lens() is not l2


def test_pad_tokens_extend_slab_not_address_space():
    p = _pool(num_slots=2, block_len=4, n_blocks=3, pad_tokens=4)
    k, _ = p.slabs[0]
    assert k.shape[2] == p.capacity + 4 == p.slab_len
    # the device table can never name a page inside the pad region
    assert int(np.asarray(p.device_block_table()).max()) \
        * p.block_len + p.block_len <= p.num_slots * p.capacity


def test_fragmentation_ratio_gauge():
    p = _pool(block_len=4)
    assert p.fragmentation_ratio() == 0.0  # idle pool
    s = p.allocate(8)
    p.set_length(s, 5)                     # 2 blocks back 5 tokens
    assert p.fragmentation_ratio() == pytest.approx(1 - 5 / 8)
    p.set_length(s, 8)
    assert p.fragmentation_ratio() == 0.0


# ---- engine acceptance: bit-identity, one dispatch per pump, TTFT ----

def _cfg(**kw):
    from paddle_tpu import serving
    base = dict(num_slots=4, block_len=8, n_blocks=4, prefill_chunk=8)
    base.update(kw)
    return serving.LLMEngineConfig(**base)


def test_engine_chunked_streams_bit_identical_to_generate(gpt_tiny):
    """Mixed lengths — including a prompt longer than prefill_chunk, so
    chunked prefill actually splits it — stream exactly what one-shot
    greedy generate() produces, with every pump issuing exactly ONE
    unified dispatch (no per-row or per-bucket dispatch fanout)."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate
    prompts = [np.arange(1, 5, dtype=np.int32),
               np.arange(3, 15, dtype=np.int32),      # 12 > chunk of 8
               np.arange(40, 49, dtype=np.int32),     # 9 -> 2 chunks
               np.arange(7, 9, dtype=np.int32)]
    refs = [np.asarray(generate(gpt_tiny, p[None, :],
                                max_new_tokens=6).numpy())[0, len(p):]
            for p in prompts]
    eng = serving.LLMEngine(gpt_tiny, _cfg(), clock=serving.SimClock())
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    while eng.has_work():
        eng.pump()
    for h, r in zip(handles, refs):
        assert np.array_equal(h.result(timeout=0), r)
    # every pump that did work issued exactly one dispatch: the lifetime
    # dispatch count is the committed step count (no retries, no probes,
    # no per-bucket prefill executables)
    assert eng._dispatch_idx == eng.decode_iterations \
        + eng.prefill_dispatches
    assert eng.metrics.snapshot()["kv_fragmentation"] == 0.0  # idle again
    eng.pool.check_balance()
    eng.stop()


def test_chunked_short_prompt_ttft_beats_bucket_baseline(gpt_tiny):
    """SimClock TTFT acceptance: a short prompt arriving behind a long
    one gets its first token after ONE chunk-width dispatch (it rides the
    long prompt's next chunk), vs the retired bucket engine where it
    waited out the long prompt's whole pow2-bucket prefill dispatch plus
    its own. Cost model: a dispatch costs its query width in ms."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    C = 8
    eng = serving.LLMEngine(
        gpt_tiny, _cfg(num_slots=2, n_blocks=16, prefill_chunk=C),
        clock=clock)
    long = eng.submit(np.arange(1, 61, dtype=np.int32), max_new_tokens=4)
    eng.pump()                             # long's chunk 0 (prefill-only)
    clock.advance(C / 1e3)
    short = eng.submit(np.arange(70, 76, dtype=np.int32),
                       max_new_tokens=4)
    idx0 = eng._dispatch_idx
    pumps = 0
    while not short.tokens_so_far():
        eng.pump()                         # mixed: long chunk + short row
        clock.advance(C / 1e3)
        pumps += 1
    assert pumps == 1                      # tok0 on its FIRST ride-along
    assert eng._dispatch_idx - idx0 == 1   # one dispatch per mixed pump
    # bucket baseline: pow2(60)=64-wide long prefill, then pow2(6)=8-wide
    # short prefill, sequential dispatches -> 72ms before short's tok0
    baseline_ms = 64 + 8
    assert short.ttft_ms is not None
    assert short.ttft_ms <= 0.5 * baseline_ms
    while eng.has_work():
        eng.pump()
    assert len(long.result(timeout=0)) == 4
    assert len(short.result(timeout=0)) == 4
    # one dispatch per pump, lifetime: prefill-only steps (long's chunks
    # with no decode rider) plus decode-carrying steps account for every
    # dispatch index — there is no separate prefill executable
    assert eng._dispatch_idx == eng.prefill_dispatches \
        + eng.decode_iterations
    eng.pool.check_balance()
    eng.stop()


# ---- chunk-granular blame (the fault-matrix scenarios) ----

@pytest.mark.fault_matrix
def test_poisoned_prefill_chunk_spares_co_scheduled_decode(gpt_tiny):
    """poison_request on a chunked-prefill row: the mixed dispatch
    (poisoned prefill chunk + innocent decode row) fails, blame probes
    implicate only the prefilling request, and the co-scheduled decode
    row is NOT evicted — its full stream stays bit-identical because
    probe results are never committed."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate
    from paddle_tpu.utils.fault_injection import FaultPlan

    good_p = np.arange(1, 4, dtype=np.int32)
    ref = np.asarray(generate(gpt_tiny, good_p[None, :],
                              max_new_tokens=6).numpy())[0, 3:]
    plan = FaultPlan.from_spec("poison_request@1")
    eng = serving.LLMEngine(
        gpt_tiny, _cfg(num_slots=2, prefill_chunk=4, dispatch_retries=0),
        clock=serving.SimClock(), fault_plan=plan)
    good = eng.submit(good_p, max_new_tokens=6)          # submit idx 0
    eng.pump()                             # good prefills solo (idx 0)
    assert good.tokens_so_far()
    bad = eng.submit(np.arange(10, 20, dtype=np.int32),  # submit idx 1,
                     max_new_tokens=4)     # 10 toks -> 3 chunks of 4
    eng.pump()      # mixed step poisoned -> probes -> quarantine bad,
    while eng.has_work():                  # good decodes on unharmed
        eng.pump()
    with pytest.raises(serving.DispatchFailedError, match="isolation") \
            as exc:
        bad.result(timeout=0)
    assert exc.value.reason == "poisoned"
    assert bad.tokens_so_far() == []       # poisoned at chunk 0
    assert np.array_equal(good.result(timeout=0), ref)
    snap = eng.metrics.snapshot()
    assert snap["quarantined"] == 1 and snap["completed"] == 1
    assert not eng.broken                  # blame absolved the breaker
    eng.pool.check_balance()
    assert eng.pool.active_slots() == 0
    eng.stop()


@pytest.mark.fault_matrix
def test_chunk1_failure_blames_mid_prefill_row_only(gpt_tiny):
    """A persistent failure first manifesting at prefill chunk k=1 (the
    request's chunk 0 already committed KV): the step + the mid-prefill
    row's solo probe raise, the decode row's probe is clean, so the
    half-prefilled request is quarantined — slot freed with its partial
    KV — while the co-scheduled decode row streams bit-identically."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate
    from paddle_tpu.utils.fault_injection import FaultPlan

    good_p = np.arange(1, 4, dtype=np.int32)
    ref = np.asarray(generate(gpt_tiny, good_p[None, :],
                              max_new_tokens=6).numpy())[0, 3:]
    # idx 0: good's solo prefill. idx 1: bad chunk0 + good decode (ok).
    # idx 2: bad chunk1 + good decode RAISES (retries=0); probes — good
    # solo decode idx 3 (clean), bad solo prefill idx 4 (raises) -> the
    # mid-prefill row is blamed; survivors re-step at idx 5.
    plan = FaultPlan.from_spec("dispatch_raise@2;dispatch_raise@4")
    eng = serving.LLMEngine(
        gpt_tiny, _cfg(num_slots=2, prefill_chunk=4, dispatch_retries=0),
        clock=serving.SimClock(), fault_plan=plan)
    good = eng.submit(good_p, max_new_tokens=6)          # submit idx 0
    eng.pump()                                           # idx 0
    bad = eng.submit(np.arange(10, 20, dtype=np.int32),  # submit idx 1
                     max_new_tokens=4)
    eng.pump()                                           # idx 1: chunk 0
    assert eng._active[bad_slot(eng, bad)].chunk_off == 4
    eng.pump()                             # idx 2 fails -> blame -> idx 5
    with pytest.raises(serving.DispatchFailedError, match="isolation") \
            as exc:
        bad.result(timeout=0)
    assert exc.value.reason == "poisoned"
    assert bad.tokens_so_far() == []       # died mid-prefill: no tokens
    while eng.has_work():
        eng.pump()
    assert np.array_equal(good.result(timeout=0), ref)
    assert sorted(plan.log) == ["dispatch_raise@2", "dispatch_raise@4"]
    snap = eng.metrics.snapshot()
    assert snap["quarantined"] == 1 and snap["completed"] == 1
    assert not eng.broken
    eng.pool.check_balance()
    assert eng.pool.active_slots() == 0
    eng.stop()


def bad_slot(eng, handle):
    for slot, req in eng._active.items():
        if req.handle is handle:
            return slot
    raise AssertionError("request not active")
