"""LoDTensor ragged metadata + sequence ops + SelectedRows sparse grads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import (LoDTensor, SelectedRows, sequence_expand,
                               sequence_mask, sequence_pad, sequence_unpad)


def _ragged():
    return LoDTensor.from_sequences([
        np.ones((2, 3), np.float32) * 1,
        np.ones((3, 3), np.float32) * 2,
        np.ones((1, 3), np.float32) * 3,
    ])


def test_lod_from_sequences_and_lengths():
    x = _ragged()
    assert x.lod == [[0, 2, 5, 6]]
    assert x.sequence_lengths() == [2, 3, 1]
    assert x.num_sequences() == 3
    assert x.tensor.shape == [6, 3]


def test_sequence_pad_unpad_roundtrip():
    x = _ragged()
    padded, lens = sequence_pad(x, pad_value=0.0)
    assert padded.shape == [3, 3, 3]
    np.testing.assert_allclose(lens.numpy(), [2, 3, 1])
    # padding positions are exactly pad_value
    assert float(padded.numpy()[0, 2].sum()) == 0.0
    assert float(padded.numpy()[2, 1:].sum()) == 0.0
    back = sequence_unpad(padded, lens)
    np.testing.assert_allclose(back.tensor.numpy(), x.tensor.numpy())
    assert back.lod == x.lod


def test_sequence_mask_matches_lengths():
    m = sequence_mask(paddle.to_tensor(np.asarray([2, 3, 1])), maxlen=4,
                      dtype="float32")
    expected = np.array([[1, 1, 0, 0], [1, 1, 1, 0], [1, 0, 0, 0]],
                        np.float32)
    np.testing.assert_allclose(m.numpy(), expected)


def test_sequence_expand_repeats_by_ref_lod():
    x = LoDTensor.from_sequences([np.asarray([[1.0]]), np.asarray([[2.0]])])
    y = LoDTensor.from_sequences([np.zeros((2, 1)), np.zeros((3, 1))])
    out = sequence_expand(x, y)
    np.testing.assert_allclose(out.tensor.numpy().ravel(),
                               [1.0, 1.0, 2.0, 2.0, 2.0])


def test_selected_rows_to_dense_and_merge():
    sr = SelectedRows(rows=[1, 3, 1], values=np.ones((3, 2), np.float32),
                      height=5)
    merged = sr.merge()
    assert sorted(np.asarray(merged.rows).tolist()) == [1, 3]
    dense = np.asarray(sr.to_dense())
    assert dense.shape == (5, 2)
    np.testing.assert_allclose(dense[1], [2.0, 2.0])  # duplicate row summed
    np.testing.assert_allclose(dense[3], [1.0, 1.0])
    np.testing.assert_allclose(dense[[0, 2, 4]], 0.0)


def test_lod_validates_offsets():
    from paddle_tpu.core import errors
    with pytest.raises(errors.InvalidArgumentError):
        LoDTensor(np.zeros((4, 2)), [[0, 3]])  # does not cover all rows
    with pytest.raises(errors.InvalidArgumentError):
        sequence_pad(_ragged(), maxlen=2)  # shorter than longest (3)


# ---- round-3 sequence-op breadth (operators/sequence_ops parity) ----

def _lt(seqs):
    from paddle_tpu.tensor.lod import LoDTensor
    return LoDTensor.from_sequences([np.asarray(s) for s in seqs])


def test_sequence_concat_interleaves():
    from paddle_tpu.tensor.lod import sequence_concat
    a = _lt([[1, 2], [5]])
    b = _lt([[3], [6, 7]])
    out = sequence_concat([a, b])
    np.testing.assert_array_equal(np.asarray(out.data), [1, 2, 3, 5, 6, 7])
    assert out.lod[-1] == [0, 3, 6]


def test_sequence_reverse_within():
    from paddle_tpu.tensor.lod import sequence_reverse
    out = sequence_reverse(_lt([[1, 2, 3], [4, 5]]))
    np.testing.assert_array_equal(np.asarray(out.data), [3, 2, 1, 5, 4])


def test_sequence_pool_modes():
    from paddle_tpu.tensor.lod import sequence_pool
    x = _lt([[1.0, 2.0, 3.0], [4.0]])
    np.testing.assert_allclose(np.asarray(sequence_pool(x, "sum").data),
                               [6.0, 4.0])
    np.testing.assert_allclose(np.asarray(sequence_pool(x, "average").data),
                               [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(sequence_pool(x, "max").data),
                               [3.0, 4.0])
    np.testing.assert_allclose(np.asarray(sequence_pool(x, "last").data),
                               [3.0, 4.0])
    np.testing.assert_allclose(np.asarray(sequence_pool(x, "sqrt").data),
                               [6.0 / np.sqrt(3), 4.0])


def test_sequence_softmax_per_sequence():
    from paddle_tpu.tensor.lod import sequence_softmax
    out = sequence_softmax(_lt([[1.0, 1.0], [0.0, 0.0, 0.0]]))
    d = np.asarray(out.data)
    np.testing.assert_allclose(d[:2], 0.5)
    np.testing.assert_allclose(d[2:], 1 / 3, rtol=1e-6)


def test_sequence_enumerate_windows():
    from paddle_tpu.tensor.lod import sequence_enumerate
    out = sequence_enumerate(_lt([[1, 2, 3], [7, 8]]), win_size=2,
                             pad_value=0)
    np.testing.assert_array_equal(
        np.asarray(out.data),
        [[1, 2], [2, 3], [3, 0], [7, 8], [8, 0]])


def test_sequence_erase():
    from paddle_tpu.tensor.lod import sequence_erase
    out = sequence_erase(_lt([[1, 2, 1, 3], [1, 1]]), tokens=[1])
    np.testing.assert_array_equal(np.asarray(out.data), [2, 3])
    assert out.lod[-1] == [0, 2, 2]


def test_sequence_expand_as():
    from paddle_tpu.tensor.lod import sequence_expand_as
    x = _lt([[10.0], [20.0]])
    # x has 2 rows; y has 2 sequences of lens 2 and 3
    y = _lt([[0, 0], [0, 0, 0]])
    from paddle_tpu.tensor.lod import LoDTensor
    x2 = LoDTensor(np.array([[10.0], [20.0]]), [[0, 1, 2]])
    out = sequence_expand_as(x2, y)
    np.testing.assert_allclose(np.asarray(out.data).reshape(-1),
                               [10, 10, 20, 20, 20])


def test_sequence_slice_reshape_scatter():
    from paddle_tpu.tensor.lod import (sequence_reshape, sequence_scatter,
                                       sequence_slice)
    x = _lt([[1, 2, 3, 4], [5, 6]])
    out = sequence_slice(x, offset=[1, 0], length=[2, 1])
    np.testing.assert_array_equal(np.asarray(out.data), [2, 3, 5])

    r = sequence_reshape(_lt([[1, 2, 3, 4], [5, 6]]), new_dim=2)
    np.testing.assert_array_equal(np.asarray(r.data),
                                  [[1, 2], [3, 4], [5, 6]])
    assert r.lod[-1] == [0, 2, 3]

    base = paddle.to_tensor(np.zeros((2, 4), np.float32))
    idx = _lt([[0, 1], [3]])
    upd = _lt([[1.0, 2.0], [9.0]])
    s = sequence_scatter(base, idx, upd)
    np.testing.assert_allclose(np.asarray(s.data),
                               [[1, 2, 0, 0], [0, 0, 0, 9]])


def test_sequence_slice_out_of_range_raises():
    from paddle_tpu.tensor.lod import LoDTensor, sequence_slice
    x = LoDTensor.from_sequences([np.array([1, 2]), np.array([3, 4])])
    with pytest.raises(Exception, match="out of range"):
        sequence_slice(x, offset=[1, 0], length=[2, 2])


def test_sequence_pool_preserves_int_dtype():
    from paddle_tpu.tensor.lod import LoDTensor, sequence_pool
    big = 16_777_217  # not representable in fp32
    x = LoDTensor.from_sequences([np.array([1, big], np.int64)])
    out = np.asarray(sequence_pool(x, "last").data)
    # stays integral (jax runs 32-bit ints framework-wide) and exact —
    # an fp32 round-trip would have collapsed big to 16_777_216
    assert np.issubdtype(out.dtype, np.integer) and out[0] == big


def test_sequence_scatter_lod_mismatch_raises():
    from paddle_tpu.tensor.lod import LoDTensor, sequence_scatter
    base = paddle.to_tensor(np.zeros((2, 4), np.float32))
    idx = LoDTensor(np.array([0, 1, 3]), [[0, 2, 3]])
    upd = LoDTensor(np.array([1.0, 2.0, 9.0]), [[0, 1, 3]])  # different lod
    with pytest.raises(Exception, match="same lod"):
        sequence_scatter(base, idx, upd)
