"""LoDTensor ragged metadata + sequence ops + SelectedRows sparse grads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import (LoDTensor, SelectedRows, sequence_expand,
                               sequence_mask, sequence_pad, sequence_unpad)


def _ragged():
    return LoDTensor.from_sequences([
        np.ones((2, 3), np.float32) * 1,
        np.ones((3, 3), np.float32) * 2,
        np.ones((1, 3), np.float32) * 3,
    ])


def test_lod_from_sequences_and_lengths():
    x = _ragged()
    assert x.lod == [[0, 2, 5, 6]]
    assert x.sequence_lengths() == [2, 3, 1]
    assert x.num_sequences() == 3
    assert x.tensor.shape == [6, 3]


def test_sequence_pad_unpad_roundtrip():
    x = _ragged()
    padded, lens = sequence_pad(x, pad_value=0.0)
    assert padded.shape == [3, 3, 3]
    np.testing.assert_allclose(lens.numpy(), [2, 3, 1])
    # padding positions are exactly pad_value
    assert float(padded.numpy()[0, 2].sum()) == 0.0
    assert float(padded.numpy()[2, 1:].sum()) == 0.0
    back = sequence_unpad(padded, lens)
    np.testing.assert_allclose(back.tensor.numpy(), x.tensor.numpy())
    assert back.lod == x.lod


def test_sequence_mask_matches_lengths():
    m = sequence_mask(paddle.to_tensor(np.asarray([2, 3, 1])), maxlen=4,
                      dtype="float32")
    expected = np.array([[1, 1, 0, 0], [1, 1, 1, 0], [1, 0, 0, 0]],
                        np.float32)
    np.testing.assert_allclose(m.numpy(), expected)


def test_sequence_expand_repeats_by_ref_lod():
    x = LoDTensor.from_sequences([np.asarray([[1.0]]), np.asarray([[2.0]])])
    y = LoDTensor.from_sequences([np.zeros((2, 1)), np.zeros((3, 1))])
    out = sequence_expand(x, y)
    np.testing.assert_allclose(out.tensor.numpy().ravel(),
                               [1.0, 1.0, 2.0, 2.0, 2.0])


def test_selected_rows_to_dense_and_merge():
    sr = SelectedRows(rows=[1, 3, 1], values=np.ones((3, 2), np.float32),
                      height=5)
    merged = sr.merge()
    assert sorted(np.asarray(merged.rows).tolist()) == [1, 3]
    dense = np.asarray(sr.to_dense())
    assert dense.shape == (5, 2)
    np.testing.assert_allclose(dense[1], [2.0, 2.0])  # duplicate row summed
    np.testing.assert_allclose(dense[3], [1.0, 1.0])
    np.testing.assert_allclose(dense[[0, 2, 4]], 0.0)


def test_lod_validates_offsets():
    from paddle_tpu.core import errors
    with pytest.raises(errors.InvalidArgumentError):
        LoDTensor(np.zeros((4, 2)), [[0, 3]])  # does not cover all rows
    with pytest.raises(errors.InvalidArgumentError):
        sequence_pad(_ragged(), maxlen=2)  # shorter than longest (3)
