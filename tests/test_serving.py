"""Serving export/predictor features (reference AnalysisPredictor,
analysis_predictor.h:82): symbolic-batch export (jax.export symbolic dims)
so one artifact serves any batch size natively."""

def test_dynamic_batch_symbolic_export(tmp_path):
    """export_model(dynamic_batch=True): the exported module carries a
    SYMBOLIC batch dim, so the predictor serves any batch size natively —
    no pad/chunk machinery (jax.export symbolic shapes)."""
    import json
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x = np.ones((2, 4), np.float32)
    path = str(tmp_path / "dyn")
    inference.export_model(model, [x], path, dynamic_batch=True)
    assert json.load(open(path + ".pdmodel.json"))["dynamic_batch"]
    pred = inference.load_predictor(path)
    rng = np.random.RandomState(0)
    for b in (1, 2, 7, 33):
        data = rng.rand(b, 4).astype(np.float32)
        (out,) = pred.run([data])
        assert out.shape == (b, 3)
        ref = model(paddle.to_tensor(data)).numpy()
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5,
                                   atol=1e-6)


def test_dynamic_batch_explicit_list_protects_aux_inputs(tmp_path):
    """An auxiliary input that coincidentally matches the batch size must
    stay static when the explicit per-input list says so."""
    import json
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    from paddle_tpu.nn.layer.layers import Layer

    class WeightedNet(Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x, class_w):
            return self.fc(x) * class_w.reshape([1, -1]).sum()

    paddle.seed(0)
    model = WeightedNet()
    x = np.ones((2, 4), np.float32)       # batch input, lead 2
    cw = np.ones((2,), np.float32)        # aux input, ALSO lead 2
    path = str(tmp_path / "aux")
    inference.export_model(model, [x, cw], path,
                           dynamic_batch=[True, False])
    pred = inference.load_predictor(path)
    out = pred.run([np.ones((7, 4), np.float32), cw])[0]
    assert out.shape == (7, 2)  # batch free, aux fixed at 2


def test_dynamic_batch_nothing_symbolized_falls_back_static(tmp_path):
    import json
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    x = np.ones((2, 4), np.float32)
    path = str(tmp_path / "none")
    with pytest.warns(UserWarning, match="symbolized no input"):
        inference.export_model(model, [x], path,
                               dynamic_batch=[False])
    meta = json.load(open(path + ".pdmodel.json"))
    assert meta["dynamic_batch"] is False  # pad/chunk fallback stays armed
    pred = inference.load_predictor(path)
    out = pred.run([np.ones((5, 4), np.float32)])[0]  # chunked static serve
    assert out.shape == (5, 2)


# ---- batching engine (ISSUE 3 tentpole): deterministic sim harness ----
#
# Every engine test runs the PRODUCTION scheduler (BatchingEngine.pump)
# under a SimClock — scripted instants, no sleeps, no thread flake.

def _engine(fn, **cfg):
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = serving.BatchingEngine(
        fn, serving.EngineConfig(**cfg), clock=clock)
    return eng, clock


def test_engine_coalesces_burst_into_batched_dispatches():
    """The acceptance bar: 64 arrivals at max_batch_size=8 coalesce into
    <= 9 dispatches (64/8 full batches + at most one max_wait flush), and
    every request still gets ITS OWN rows back."""
    import numpy as np
    from paddle_tpu import serving

    calls = []

    def fn(args):
        calls.append(args[0].shape[0])
        return [args[0] * 2.0]

    eng, _clock = _engine(fn, max_batch_size=8, max_wait_ms=10.0)
    mk = lambda i: [np.full((1, 4), float(i), np.float32)]  # noqa: E731
    trace = serving.poisson_trace(64, rate_hz=2000.0, make_inputs=mk, seed=0)
    report = serving.replay(eng, trace)

    assert report.outcomes == ["completed"] * 64
    assert report.dispatches <= 9, report.dispatches
    assert len(calls) == report.dispatches
    assert report.metrics["dispatches"] == report.dispatches
    for i, res in enumerate(report.results):
        np.testing.assert_allclose(res[0], np.full((1, 4), 2.0 * i))


def test_engine_flushes_partial_batch_on_max_wait():
    """A lone request must not wait for a full batch: the max_wait_ms timer
    flushes it — at the exact flush instant, on the SimClock."""
    import numpy as np
    from paddle_tpu import serving

    eng, clock = _engine(lambda a: [a[0] + 1.0],
                         max_batch_size=8, max_wait_ms=5.0)
    fut = eng.submit([np.zeros((1, 2), np.float32)])
    assert eng.pump() == 0          # not due yet: 1 row, no time passed
    clock.advance(0.005)            # exactly max_wait_ms
    assert eng.pump() == 1
    np.testing.assert_allclose(fut.result(timeout=0)[0], np.ones((1, 2)))
    eng.stop()


def test_engine_deadline_dropped_before_dispatch():
    """An expired request is dropped at batch formation: its rows NEVER
    reach predict_fn, and its future fails with DeadlineExceededError."""
    import numpy as np
    import pytest
    from paddle_tpu import serving

    seen_rows = []

    def fn(args):
        seen_rows.append(args[0][:, 0].tolist())
        return [args[0]]

    eng, clock = _engine(fn, max_batch_size=8, max_wait_ms=50.0)
    doomed = eng.submit([np.full((1, 1), -1.0, np.float32)], deadline_ms=2.0)
    clock.advance(0.003)            # past the deadline, before any flush
    ok = eng.submit([np.full((1, 1), 7.0, np.float32)])
    clock.advance(0.050)
    eng.pump()
    eng.stop()
    with pytest.raises(serving.DeadlineExceededError):
        doomed.result(timeout=0)
    np.testing.assert_allclose(ok.result(timeout=0)[0], [[7.0]])
    assert all(-1.0 not in rows for rows in seen_rows)  # never dispatched
    assert eng.metrics.counters["expired"] == 1


def test_engine_admission_fast_fails_when_queue_full():
    import numpy as np
    import pytest
    from paddle_tpu import serving

    eng, _clock = _engine(lambda a: [a[0]], max_batch_size=64,
                          max_wait_ms=1000.0, max_queue_depth=2)
    x = [np.zeros((1, 1), np.float32)]
    eng.submit(x)
    eng.submit(x)
    with pytest.raises(serving.RejectedError):
        eng.submit(x)
    assert eng.metrics.counters["rejected"] == 1
    assert eng.metrics.reject_reasons.get("queue_full") == 1
    eng.stop()  # drains the two accepted requests
    assert eng.metrics.counters["completed"] == 2
    with pytest.raises(serving.RejectedError):  # stopped engine rejects
        eng.submit(x)


def test_engine_pow2_bucketing_static_vs_native_dynamic():
    """Static exports get pow2-padded dispatch shapes (bounded executable
    cache); a dynamic_batch engine dispatches the exact coalesced size."""
    import numpy as np
    from paddle_tpu import serving

    for dynamic, expect in ((False, 8), (True, 5)):
        shapes = []

        def fn(args, _s=shapes):
            _s.append(args[0].shape[0])
            return [args[0] * 3.0]

        clock = serving.SimClock()
        eng = serving.BatchingEngine(
            fn, serving.EngineConfig(max_batch_size=8, max_wait_ms=1.0),
            clock=clock, dynamic_batch=dynamic)
        futs = [eng.submit([np.full((1, 2), float(i), np.float32)])
                for i in range(5)]
        clock.advance(0.001)
        eng.pump()
        eng.stop()
        assert shapes == [expect], (dynamic, shapes)
        for i, f in enumerate(futs):  # padding never leaks into results
            np.testing.assert_allclose(f.result(timeout=0)[0],
                                       np.full((1, 2), 3.0 * i))


def test_engine_incompatible_requests_never_share_a_dispatch():
    """Independent clients posting different trailing shapes / dtypes /
    input counts must not poison each other's batch (or kill the scheduler
    with a failed cross-request concatenate): each incompatible request
    forms its own batch and every future resolves."""
    import numpy as np
    from paddle_tpu import serving

    shapes = []

    def fn(args):
        shapes.append(tuple(a.shape for a in args))
        return [args[0] * 2.0]

    eng, clock = _engine(fn, max_batch_size=8, max_wait_ms=1.0)
    f_a = eng.submit([np.ones((1, 2), np.float32)])
    f_e = eng.submit([np.ones((1, 2), np.float32)])   # coalesces with f_a
    f_b = eng.submit([np.ones((1, 3), np.float32)])   # different trailing
    f_c = eng.submit([np.ones((1, 2), np.float64)])   # different dtype
    f_d = eng.submit([np.ones((1, 2), np.float32),    # different arity
                      np.ones((1,), np.float32)])
    clock.advance(0.001)
    assert eng.pump() == 4            # [a+e], [b], [c], [d]
    eng.stop()
    for f in (f_a, f_b, f_c, f_d, f_e):
        f.result(timeout=0)           # all answered, none stranded
    np.testing.assert_allclose(f_e.result(timeout=0)[0],
                               np.full((1, 2), 2.0))
    assert shapes[0][0][0] == 2       # a+e genuinely shared one dispatch


def test_engine_dispatch_failure_does_not_kill_scheduler():
    """A predict_fn blow-up fails that batch's futures and the engine keeps
    serving later requests on the same (production) scheduler thread."""
    import numpy as np
    import pytest
    from paddle_tpu import serving

    def fn(args):
        if float(args[0].flat[0]) < 0:
            raise RuntimeError("model exploded")
        return [args[0] + 1.0]

    eng = serving.BatchingEngine(
        fn, serving.EngineConfig(max_batch_size=1, max_wait_ms=0.0))
    eng.start()
    bad = eng.submit([np.full((1, 1), -1.0, np.float32)])
    with pytest.raises(RuntimeError, match="model exploded"):
        bad.result(timeout=10)
    ok = eng.submit([np.full((1, 1), 3.0, np.float32)])
    np.testing.assert_allclose(ok.result(timeout=10)[0], [[4.0]])
    eng.stop()
    assert eng.metrics.counters["failed"] == 1


def test_engine_stop_drain_timeout_fails_queued_requests():
    """A drain that exceeds its timeout must not strand queued futures:
    they fail with RejectedError instead of blocking their callers until
    the per-request future timeout."""
    import threading
    import numpy as np
    import pytest
    from paddle_tpu import serving

    started = threading.Event()
    release = threading.Event()

    def fn(args):
        started.set()
        release.wait(20.0)          # wedge the in-flight dispatch
        return [args[0]]

    eng = serving.BatchingEngine(
        fn, serving.EngineConfig(max_batch_size=1, max_wait_ms=0.0))
    eng.start()
    f1 = eng.submit([np.zeros((1, 1), np.float32)])
    f2 = eng.submit([np.zeros((1, 1), np.float32)])
    assert started.wait(10.0)           # f1's dispatch is in flight
    eng.stop(drain=True, timeout=0.2)   # scheduler stuck dispatching f1
    with pytest.raises(serving.RejectedError):
        f2.result(timeout=5)
    assert eng.metrics.reject_reasons.get("drain_timeout", 0) >= 1
    release.set()
    f1.result(timeout=10)               # in-flight dispatch still lands


def test_engine_oversized_request_pads_to_pow2():
    """A single request larger than max_batch_size dispatches on a pow2
    shape (bounded executable cache even for oversized traffic) and the
    padding never leaks into its result."""
    import numpy as np
    from paddle_tpu import serving

    shapes = []

    def fn(args):
        shapes.append(args[0].shape[0])
        return [args[0] + 1.0]

    eng, _clock = _engine(fn, max_batch_size=8, max_wait_ms=1.0)
    fut = eng.submit([np.zeros((11, 2), np.float32)])
    assert eng.pump() == 1           # 11 rows >= max_batch: due immediately
    eng.stop()
    assert shapes == [16], shapes
    assert fut.result(timeout=0)[0].shape == (11, 2)


def test_engine_max_request_rows_admission_cap():
    import numpy as np
    import pytest
    from paddle_tpu import serving

    eng, _clock = _engine(lambda a: [a[0]], max_batch_size=8,
                          max_request_rows=4)
    with pytest.raises(serving.RejectedError, match="max_request_rows"):
        eng.submit([np.zeros((5, 1), np.float32)])
    assert eng.metrics.reject_reasons.get("too_many_rows") == 1
    eng.submit([np.zeros((4, 1), np.float32)])   # at the cap: admitted
    eng.stop()
    assert eng.metrics.counters["completed"] == 1


def test_engine_from_predictor_static_and_dynamic(tmp_path):
    """End-to-end over REAL export artifacts, both flavors: from_predictor
    picks the bucketing mode from the export's dynamic_batch flag and the
    coalesced results match the eager model exactly."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn, serving

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x0 = np.ones((4, 4), np.float32)
    rng = np.random.RandomState(1)
    mk = lambda i: [rng.rand(1, 4).astype(np.float32)]  # noqa: E731

    for name, dyn in (("static", False), ("dynamic", True)):
        path = str(tmp_path / name)
        inference.export_model(model, [x0], path, dynamic_batch=dyn)
        pred = inference.load_predictor(path)
        eng = serving.BatchingEngine.from_predictor(
            pred, serving.EngineConfig(max_batch_size=8, max_wait_ms=2.0),
            clock=serving.SimClock())
        assert eng.dynamic_batch is dyn
        trace = serving.uniform_trace(12, 0.0001, mk)
        report = serving.replay(eng, trace)
        assert report.outcomes == ["completed"] * 12
        assert report.dispatches <= 3  # 12 rows / max_batch 8 -> 2-3
        for a, res in zip(trace, report.results):
            ref = model(paddle.to_tensor(a.inputs[0])).numpy()
            np.testing.assert_allclose(res[0], np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)


def test_predictor_concurrent_explicit_feed_is_thread_safe(tmp_path):
    """Two threads hammering ONE predictor with explicit feeds must each get
    their own answers (run() computes from caller arrays, not the shared IO
    handles) — the property the batching engine's dispatch path relies on."""
    import threading
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn

    paddle.seed(0)
    model = nn.Linear(6, 2)
    path = str(tmp_path / "mt")
    inference.export_model(model, [np.ones((2, 6), np.float32)], path)
    pred = inference.load_predictor(path)
    rng = np.random.RandomState(0)
    feeds = [rng.rand(2, 6).astype(np.float32) for _ in range(40)]
    refs = [np.asarray(model(paddle.to_tensor(f)).numpy()) for f in feeds]
    errs = []

    def worker(idx):
        try:
            for i in range(idx, len(feeds), 2):
                (out,) = pred.run([feeds[i]])
                np.testing.assert_allclose(out, refs[i], rtol=1e-5,
                                           atol=1e-5)
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert not errs, errs


# ---- serving metrics ----

def test_metrics_render_parse_roundtrip():
    from paddle_tpu import serving

    m = serving.ServingMetrics()
    m.on_submit(1)
    m.on_complete(4.0)
    m.on_reject("queue_full")
    m.on_dispatch(rows=6, n_requests=3, padded_rows=8, dispatch_ms=2.0,
                  queue_depth=0)
    flat = serving.parse_exposition(m.render())
    assert flat['pdtpu_serving_requests_total{outcome="submitted"}'] == 1
    assert flat['pdtpu_serving_requests_total{outcome="completed"}'] == 1
    assert flat['pdtpu_serving_requests_total{outcome="rejected"}'] == 1
    assert flat["pdtpu_serving_dispatches_total"] == 1
    assert flat['pdtpu_serving_batch_rows_bucket{le="8"}'] == 1
    assert flat["pdtpu_serving_batch_rows_sum"] == 6
    snap = m.snapshot()
    assert snap["mean_batch_rows"] == 6.0
    assert snap["p50_ms"] == 4.0


# ---- HTTP front end (in-process) ----

def test_serving_server_endpoints_and_hardening():
    """/predict round-trips through the engine; /healthz and /metrics
    report; a malformed POST (no Content-Length) gets 411 — the shared
    fleet read_request_body hardening — and the server survives it."""
    import json
    import socket
    import urllib.error
    import urllib.request
    import numpy as np
    from paddle_tpu import serving

    W = np.arange(6, dtype=np.float32).reshape(3, 2)
    eng = serving.BatchingEngine(
        lambda a: [a[0] @ W],
        serving.EngineConfig(max_batch_size=4, max_wait_ms=2.0))
    srv = serving.ServingServer(eng, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def post(obj):
        req = urllib.request.Request(
            base + "/predict", data=json.dumps(obj).encode(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        code, out = post({"inputs": [[[1.0, 2.0, 3.0]]]})
        assert code == 200
        np.testing.assert_allclose(
            out["outputs"][0], (np.array([[1.0, 2.0, 3.0]]) @ W).tolist())

        code, out = post({"wrong_key": 1})
        assert code == 400 and "inputs" in out["error"]

        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            flat = serving.parse_exposition(r.read().decode())
        assert flat['pdtpu_serving_requests_total{outcome="completed"}'] == 1

        # malformed client: POST with no Content-Length -> 411, not a 500
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.sendall(b"POST /predict HTTP/1.1\r\nHost: x\r\n\r\n")
        status = s.recv(200).decode().splitlines()[0]
        s.close()
        assert "411" in status, status

        code, _ = post({"inputs": [[[0.0, 0.0, 1.0]]]})  # still serving
        assert code == 200
    finally:
        srv.stop()
        srv.stop()  # idempotent, same contract as KVServer.stop


def test_kv_server_put_hardening_and_idempotent_stop():
    """Satellite: the fleet KV server itself survives a malformed PUT
    (missing / garbage Content-Length) and double-stop."""
    import socket
    from paddle_tpu.distributed.fleet.utils import http_server

    kv = http_server.KVServer(0)
    kv.start()
    port = kv._server.server_address[1]

    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(b"PUT /k HTTP/1.1\r\nHost: x\r\n\r\n")       # no length
    assert "411" in s.recv(200).decode().splitlines()[0]
    s.close()
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.sendall(b"PUT /k HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: banana\r\n\r\n")           # garbage length
    assert "400" in s.recv(200).decode().splitlines()[0]
    s.close()

    client = http_server.KVClient(f"127.0.0.1:{port}")
    assert client.put("/k", "v") and client.get("/k") == "v"  # still alive
    kv.stop()
    kv.stop()  # must not raise on the closed socket


# ---- graceful drain (the fault-matrix scenario) ----

import os     # noqa: E402
import signal  # noqa: E402
import subprocess  # noqa: E402
import sys    # noqa: E402
import time   # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def _start_serving_worker(workdir, env_extra=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.join(FIXTURES, "serving_worker.py"),
         str(workdir)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    port_file = os.path.join(str(workdir), "port")
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            return proc, int(open(port_file).read())
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    proc.kill()
    _, err = proc.communicate(timeout=30)
    raise AssertionError(f"serving worker never bound a port: {err[-3000:]}")


import pytest  # noqa: E402


@pytest.mark.fault_matrix
def test_sigterm_drains_accepted_requests_and_exits_zero(tmp_path):
    """Drain contract (docs/serving.md, mirroring the ResilientTrainer
    preemption matrix): SIGTERM mid-traffic → admissions stop (late
    requests get 503 or connection-refused), every ACCEPTED request still
    gets its answer, the process exits 0, and the final metrics snapshot
    reconciles exactly with what the clients observed."""
    import json
    import threading
    import urllib.error
    import urllib.request
    import numpy as np
    from paddle_tpu import serving

    proc, port = _start_serving_worker(
        tmp_path, {"SERVE_DISPATCH_SLEEP_S": "0.05", "SERVE_MAX_BATCH": "4",
                   "PDTPU_FLIGHT_DIR": str(tmp_path)})
    base = f"http://127.0.0.1:{port}"
    W = np.random.RandomState(0).randn(3, 2).astype(np.float32)

    lock = threading.Lock()
    oks, rejected, conn_failed = [], [], []

    def client(tid):
        rng = np.random.RandomState(tid)
        t_end = time.time() + 20
        while time.time() < t_end:
            x = rng.rand(1, 3).astype(np.float32)
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"inputs": [x.tolist()]}).encode(),
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    out = json.loads(r.read())["outputs"][0]
                np.testing.assert_allclose(out, (x @ W).tolist(),
                                           rtol=1e-5, atol=1e-5)
                with lock:
                    oks.append(tid)
            except urllib.error.HTTPError as e:
                assert e.code == 503, e.code  # draining fast-fail only
                with lock:
                    rejected.append(tid)
            except (urllib.error.URLError, ConnectionError, OSError):
                with lock:  # accept loop closed: request never admitted
                    conn_failed.append(tid)
                return

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    [t.start() for t in threads]
    deadline = time.time() + 30
    while time.time() < deadline:  # let real traffic build up first
        with lock:
            if len(oks) >= 8:
                break
        time.sleep(0.02)
    proc.send_signal(signal.SIGTERM)   # lands with requests in flight
    _, err = proc.communicate(timeout=60)
    [t.join(timeout=60) for t in threads]

    assert proc.returncode == 0, err[-3000:]   # graceful drain, not a crash
    assert len(oks) >= 8
    metrics_path = tmp_path / "metrics_final.txt"
    assert metrics_path.exists(), "drain must write the final snapshot"
    flat = serving.parse_exposition(metrics_path.read_text())
    # every client-observed 200 is a completed request and vice versa: no
    # accepted request was dropped, no response was fabricated
    assert flat['pdtpu_serving_requests_total{outcome="completed"}'] == \
        len(oks)
    assert flat['pdtpu_serving_requests_total{outcome="rejected"}'] == \
        len(rejected)
    assert flat['pdtpu_serving_requests_total{outcome="submitted"}'] == \
        len(oks)  # accepted == answered; nothing pending at exit
    assert flat["pdtpu_serving_queue_depth"] == 0

    # ISSUE 9: SIGTERM must leave a black-box dump before the drain starts
    # (so a wedged drain + supervisor SIGKILL still leaves evidence)
    dump_path = tmp_path / f"pdtpu_flight_{proc.pid}.json"
    assert dump_path.exists(), "SIGTERM handler must dump the flight ring"
    dump = json.loads(dump_path.read_text())
    assert dump["reason"] == "sigterm"
    assert any(e["kind"] == "sigterm" for e in dump["events"])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "flight_recorder.py"),
         str(dump_path)], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "sigterm" in r.stdout


# ---- engine supervision: watchdog + circuit breaker (ISSUE 6) ----

def test_supervisor_watchdog_abandons_hung_dispatch():
    """Real wall-clock watchdog: a dispatch that blocks past the budget
    raises DispatchHungError and the worker thread is abandoned."""
    import threading
    import time as _time
    from paddle_tpu.serving import DispatchHungError, EngineSupervisor

    release = threading.Event()
    sup = EngineSupervisor(dispatch_timeout_s=0.2)
    t0 = _time.monotonic()
    with pytest.raises(DispatchHungError, match="watchdog"):
        sup.run(lambda: release.wait(30), label="decode")
    assert _time.monotonic() - t0 < 10          # did NOT wait the full 30s
    assert sup.stats["watchdog_fires"] == 1
    release.set()
    # a healthy dispatch under the same supervisor still works
    assert sup.run(lambda: 42) == 42


def test_supervisor_types_failures_and_breaker_protocol():
    from paddle_tpu.serving import (DispatchFailedError, EngineSupervisor)

    trips = []
    sup = EngineSupervisor(breaker_threshold=2,
                           on_trip=lambda: trips.append(1))
    with pytest.raises(DispatchFailedError, match="ValueError") as exc:
        sup.run(lambda: (_ for _ in ()).throw(ValueError("boom")))
    assert exc.value.reason == "raise"
    assert isinstance(exc.value.__cause__, ValueError)
    # breaker counts CONSECUTIVE engine-level failures only
    assert sup.record_failure() is False and not sup.open
    sup.record_success()                        # success resets the streak
    assert sup.record_failure() is False
    sup.absolve()                               # quarantine resets it too
    assert sup.stats["quarantines"] == 1
    assert sup.record_failure() is False
    assert sup.record_failure() is True         # 2nd consecutive: trips
    assert sup.open and trips == [1]
    sup.record_failure()                        # already open: no re-trip
    assert sup.stats["breaker_trips"] == 1 and trips == [1]
    snap = sup.snapshot()
    assert snap["circuit_open"] is True


@pytest.mark.fault_matrix
def test_engine_breaker_opens_after_repeated_dispatch_failures():
    """BatchingEngine supervision: every batch dispatch failure charges
    the breaker; at breaker_threshold it opens — pending requests fail
    typed, new submits reject 'circuit_open', metrics expose the gauge."""
    import numpy as np
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan

    plan = FaultPlan.from_spec("dispatch_raise@0;dispatch_raise@1")
    clock = serving.SimClock()
    broke = []
    eng = serving.BatchingEngine(
        lambda a: [a[0] * 2],
        serving.EngineConfig(max_batch_size=2, max_wait_ms=0.0,
                             breaker_threshold=2),
        clock=clock, fault_plan=plan, on_break=lambda: broke.append(1))
    f1 = eng.submit([np.ones((1, 2), np.float32)])
    eng.pump()                                  # dispatch 0 raises
    with pytest.raises(serving.DispatchFailedError):
        f1.result(timeout=0)
    assert not eng.broken
    f2 = eng.submit([np.ones((1, 2), np.float32)])
    eng.pump()                                  # dispatch 1 raises: trips
    with pytest.raises(serving.DispatchFailedError):
        f2.result(timeout=0)
    assert eng.broken and broke == [1]
    with pytest.raises(serving.RejectedError, match="circuit") as exc:
        eng.submit([np.ones((1, 2), np.float32)])
    assert exc.value.reason == "circuit_open"
    snap = eng.metrics.snapshot()
    assert snap["circuit_open"] is True
    assert snap["dispatch_failures"] == {"raise": 2}
    flat = serving.parse_exposition(eng.metrics.render())
    assert flat["pdtpu_serving_circuit_open"] == 1
    assert flat['pdtpu_serving_dispatch_failures_total{kind="raise"}'] == 2
    # a recovered dispatch never un-trips it: the breaker is terminal
    eng.stop(drain=False)


def test_http_backpressure_429_and_broken_healthz():
    """Overload rejects surface as HTTP 429 + Retry-After (back off, come
    back), while a tripped breaker flips /healthz to 503 'broken'."""
    import json
    import threading
    import urllib.error
    import urllib.request
    import numpy as np
    from paddle_tpu import serving

    gate = threading.Event()
    entered = threading.Event()

    def slow_predict(arrays):
        entered.set()
        gate.wait(30)
        return [arrays[0] * 2]

    eng = serving.BatchingEngine(
        slow_predict,
        serving.EngineConfig(max_batch_size=1, max_wait_ms=0.0,
                             max_queue_depth=1, retry_after_s=2.5),
        on_break=lambda: None)      # keep the server up after the trip
    srv = serving.ServingServer(eng, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def post_async(results):
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [[[1.0, 2.0]]]}).encode(),
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                results.append(r.status)
        except urllib.error.HTTPError as e:
            results.append(e.code)

    try:
        # rq A occupies the (blocked) dispatch, rq B fills the queue
        done_a, done_b = [], []
        threading.Thread(target=post_async, args=(done_a,)).start()
        assert entered.wait(20)               # A is inside slow_predict
        threading.Thread(target=post_async, args=(done_b,)).start()
        deadline = time.time() + 20
        while eng.metrics.queue_depth < 1 and time.time() < deadline:
            time.sleep(0.01)                  # B is queued (depth 1/1)
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [[[9.0, 9.0]]]}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 429          # overload: typed backpressure
        assert exc.value.headers["Retry-After"] == "2.5"
        assert json.loads(exc.value.read())["reason"] == "queue_full"
        gate.set()                            # unblock A, then B completes
        deadline = time.time() + 30
        while (not done_a or not done_b) and time.time() < deadline:
            time.sleep(0.01)
        assert done_a == [200] and done_b == [200]

        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        # trip the breaker: /healthz must flip to 503 {"status": "broken"}
        for _ in range(eng.config.breaker_threshold):
            eng.supervisor.record_failure()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/healthz", timeout=10)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "broken"
        # and /predict now fast-fails 503 circuit_open (not retryable)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["reason"] == "circuit_open"
    finally:
        gate.set()
        srv.stop()


def test_breaker_trip_drains_server_via_on_break():
    """Default wiring: a breaker trip starts the server drain on its own
    thread, so an external supervisor sees unhealthy -> drained."""
    import numpy as np
    from paddle_tpu import serving

    eng = serving.BatchingEngine(
        lambda a: [a[0]],
        serving.EngineConfig(max_batch_size=1, max_wait_ms=0.0,
                             breaker_threshold=1))
    srv = serving.ServingServer(eng, port=0).start()
    assert eng.on_break is not None           # server claimed the hook
    eng.supervisor.record_failure()           # trips at threshold 1
    assert srv._stopped_event.wait(timeout=30), "breaker drain never ran"
    assert eng.broken and eng.draining
