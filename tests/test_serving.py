"""Serving export/predictor features (reference AnalysisPredictor,
analysis_predictor.h:82): symbolic-batch export (jax.export symbolic dims)
so one artifact serves any batch size natively."""

def test_dynamic_batch_symbolic_export(tmp_path):
    """export_model(dynamic_batch=True): the exported module carries a
    SYMBOLIC batch dim, so the predictor serves any batch size natively —
    no pad/chunk machinery (jax.export symbolic shapes)."""
    import json
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x = np.ones((2, 4), np.float32)
    path = str(tmp_path / "dyn")
    inference.export_model(model, [x], path, dynamic_batch=True)
    assert json.load(open(path + ".pdmodel.json"))["dynamic_batch"]
    pred = inference.load_predictor(path)
    rng = np.random.RandomState(0)
    for b in (1, 2, 7, 33):
        data = rng.rand(b, 4).astype(np.float32)
        (out,) = pred.run([data])
        assert out.shape == (b, 3)
        ref = model(paddle.to_tensor(data)).numpy()
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-5,
                                   atol=1e-6)


def test_dynamic_batch_explicit_list_protects_aux_inputs(tmp_path):
    """An auxiliary input that coincidentally matches the batch size must
    stay static when the explicit per-input list says so."""
    import json
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    from paddle_tpu.nn.layer.layers import Layer

    class WeightedNet(Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x, class_w):
            return self.fc(x) * class_w.reshape([1, -1]).sum()

    paddle.seed(0)
    model = WeightedNet()
    x = np.ones((2, 4), np.float32)       # batch input, lead 2
    cw = np.ones((2,), np.float32)        # aux input, ALSO lead 2
    path = str(tmp_path / "aux")
    inference.export_model(model, [x, cw], path,
                           dynamic_batch=[True, False])
    pred = inference.load_predictor(path)
    out = pred.run([np.ones((7, 4), np.float32), cw])[0]
    assert out.shape == (7, 2)  # batch free, aux fixed at 2


def test_dynamic_batch_nothing_symbolized_falls_back_static(tmp_path):
    import json
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu import inference, nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    x = np.ones((2, 4), np.float32)
    path = str(tmp_path / "none")
    with pytest.warns(UserWarning, match="symbolized no input"):
        inference.export_model(model, [x], path,
                               dynamic_batch=[False])
    meta = json.load(open(path + ".pdmodel.json"))
    assert meta["dynamic_batch"] is False  # pad/chunk fallback stays armed
    pred = inference.load_predictor(path)
    out = pred.run([np.ones((5, 4), np.float32)])[0]  # chunked static serve
    assert out.shape == (5, 2)
