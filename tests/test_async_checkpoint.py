"""Continuous async checkpointing + exact resume (ISSUE 15 tentpole).

In-process units cover the snapshot→ring→writer pipeline (typed
drop-oldest backpressure, emergency save, cursor round-trip), the
restore-time scrubber (certified-but-corrupt quarantine, torn-save and
stray-file handling), the GC retention floor, sharded-save certification
refusals, ring-served NaN rollback, the pdtpu_train_ckpt_* exposition,
and the acceptance bar: at equal frequency the async tier's BLOCKING
checkpoint seconds sit strictly below a synchronous baseline while the
goodput ledger's phases still tile the wall.

Subprocess scenarios (`fault_matrix`-marked, collected by
tools/check_fault_matrix.py) kill a real worker mid-background-persist
(kill@N:persist / kill@N:mid_save), tear a certified write
(ckpt_torn_write@N, scrubbed on resume), and SIGTERM it mid-run
(emergency save reconciled against the flight dump) — each asserting the
exact-resume contract: the stitched loss trajectory across killed +
resumed runs is BIT-IDENTICAL to an uninterrupted run's.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.checkpoint import (
    AsyncCheckpointManager, CheckpointManager, load_sharded, restore_rng,
    rng_cursor, save_sharded, scrub_checkpoints)
from paddle_tpu.distributed.resilient import (
    PREEMPT_MARKER, ResilientConfig, ResilientTrainer)
from paddle_tpu.obs.flight_recorder import flight_recorder
from paddle_tpu.utils import fault_injection
from paddle_tpu.utils.fault_injection import FaultPlan

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
REPO = os.path.join(os.path.dirname(__file__), "..")


def _leaf(x):
    return np.asarray(getattr(x, "data", x))


# ---- snapshot pipeline ----

def test_snapshot_ring_persist_and_cursor_roundtrip(tmp_path):
    mgr = AsyncCheckpointManager(str(tmp_path), max_to_keep=10)
    state = {"w": np.arange(8, dtype=np.float32), "meta": {"k": 3}}
    cursor = {"next": 2, "pos": 7}
    mgr.snapshot(2, state, cursor=cursor)
    state["w"][:] = -1.0  # the ring copy must be OWNED, not a view
    mgr.wait_until_finished()
    assert mgr.all_steps() == [2] and mgr.verify(2)
    assert mgr.read_cursor(2) == cursor
    disk = mgr.restore(2)
    snap = mgr.newest_snapshot()
    ring = mgr.ring_state(snap)
    np.testing.assert_array_equal(_leaf(disk["w"]),
                                  np.arange(8, dtype=np.float32))
    np.testing.assert_array_equal(_leaf(ring["w"]), _leaf(disk["w"]))
    assert disk["meta"] == ring["meta"] == {"k": 3}
    stats = mgr.stats()
    assert stats["snapshots"] == 1 and stats["persisted"] == 1
    assert stats["dropped"] == 0 and stats["queue_depth"] == 0
    assert stats["blocking_seconds_total"] > 0
    mgr.close()


def test_backpressure_drops_oldest_pending_never_latest(tmp_path):
    mgr = AsyncCheckpointManager(str(tmp_path), max_to_keep=10,
                                 max_pending=1, ring_size=2)
    gate = threading.Event()
    orig = mgr._sync.save

    def gated_save(step, state, force=False, cursor=None):
        gate.wait(timeout=30)
        orig(step, state, force=force, cursor=cursor)

    mgr._sync.save = gated_save
    mgr.snapshot(1, {"w": np.ones(4, np.float32)})
    # wait until the writer has snapshot 1 in flight (blocked in the
    # gated save) so the later snapshots queue behind it deterministically
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with mgr._cv:
            if mgr._in_flight is not None and not mgr._pending:
                break
        time.sleep(0.005)
    else:
        pytest.fail("writer never picked up snapshot 1")
    mgr.snapshot(2, {"w": np.full(4, 2.0, np.float32)})
    mgr.snapshot(3, {"w": np.full(4, 3.0, np.float32)})
    mgr.snapshot(4, {"w": np.full(4, 4.0, np.float32)})
    gate.set()
    mgr.wait_until_finished()
    stats = mgr.stats()
    # 2 and 3 were shed (oldest pending); 1 (in flight) and 4 (latest)
    # persisted — the latest snapshot is never the one dropped
    assert stats["dropped"] == 2 and stats["persisted"] == 2
    assert mgr.all_steps() == [1, 4]
    assert mgr.newest_snapshot().step == 4
    lag = [e for e in flight_recorder().snapshot()["events"]
           if e["kind"] == "ckpt_lag"]
    assert lag and lag[-1]["policy"] == "drop_oldest_pending"
    assert lag[-1]["newest_step"] == 4
    mgr.close()


def test_emergency_save_persists_newest_ring_snapshot(tmp_path):
    # wedge the background writer on snapshot 1 (ckpt_io_stall fires
    # before it takes the disk lock), then emergency-save while it sleeps
    fault_injection.set_global_plan(FaultPlan.from_spec(
        "ckpt_io_stall@1:1.0"))
    try:
        mgr = AsyncCheckpointManager(str(tmp_path), max_to_keep=10)
        mgr.snapshot(1, {"w": np.ones(4, np.float32)})
        mgr.snapshot(2, {"w": np.full(4, 2.0, np.float32)})
        assert mgr.emergency_save() == 2
        assert mgr.latest_step() == 2  # on disk before the writer woke up
        mgr.wait_until_finished()
        stats = mgr.stats()
        assert stats["emergency_saves"] == 1
        assert stats["persisted"] == 2  # writer's 1 + the emergency 2
        assert sorted(mgr.all_steps()) == [1, 2]
        # emergency persists book as BLOCKING seconds (signal path)
        assert stats["blocking_seconds_total"] > 0
        kinds = [e["kind"] for e in flight_recorder().snapshot()["events"]]
        assert "ckpt_emergency" in kinds
        mgr.close()
    finally:
        fault_injection.set_global_plan(None)


def test_emergency_save_empty_ring_returns_none(tmp_path):
    mgr = AsyncCheckpointManager(str(tmp_path))
    assert mgr.emergency_save() is None
    mgr.close()


# ---- restore-time scrubber ----

def test_scrubber_quarantines_certified_but_corrupt(tmp_path):
    sync = CheckpointManager(str(tmp_path), max_to_keep=10, use_orbax=False)
    sync.save(1, {"w": np.ones(4, np.float32)})
    sync.save(2, {"w": np.full(4, 2.0, np.float32)})
    with open(sync._data_path(2), "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")  # bit rot under a valid manifest
    assert sync.latest_step() == 1  # verify() already distrusts it...
    report = scrub_checkpoints(str(tmp_path))
    assert report["clean"] == [1]
    (q,) = report["quarantined"]
    assert q["step"] == 2 and q["file"] == "step_2.pdckpt"
    assert "crc32 mismatch" in q["reason"]
    qdir = tmp_path / "step_2.corrupt"
    assert (qdir / "step_2.pdckpt").exists()
    assert (qdir / "step_2.manifest.json").exists()
    # ...but the scrubber removes it from the namespace entirely, so a
    # later writer can reuse step 2 without colliding with rotten bytes
    assert not os.path.exists(sync._data_path(2))
    corrupt = [e for e in flight_recorder().snapshot()["events"]
               if e["kind"] == "ckpt_corrupt" and e.get("step") == 2]
    assert corrupt and corrupt[-1]["file"] == "step_2.pdckpt"


def test_scrubber_torn_save_and_strays(tmp_path):
    sync = CheckpointManager(str(tmp_path), max_to_keep=10, use_orbax=False)
    sync.save(1, {"w": np.ones(4, np.float32)})
    # a data file with no manifest = a save that died mid-sequence
    with open(os.path.join(str(tmp_path), "step_3.pdckpt"), "wb") as f:
        f.write(b"partial")
    # strays that don't parse as step files must be left alone
    for stray in ("step_latest.pdckpt", "notes.txt"):
        with open(os.path.join(str(tmp_path), stray), "w") as f:
            f.write("x")
    report = scrub_checkpoints(str(tmp_path))
    assert report["clean"] == [1]
    (q,) = report["quarantined"]
    assert q["step"] == 3 and "no manifest" in q["reason"]
    assert (tmp_path / "step_3.corrupt" / "step_3.pdckpt").exists()
    assert (tmp_path / "step_latest.pdckpt").exists()
    assert (tmp_path / "notes.txt").exists()
    # and all_steps() skips the unparseable stray instead of crashing
    assert sync.all_steps() == [1]


def test_gc_never_deletes_newest_certified_step(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=0, use_orbax=False)
    mgr.save(1, {"w": np.ones(2, np.float32)})
    mgr.save(2, {"w": np.full(2, 2.0, np.float32)})
    # max_to_keep=0 would naively delete everything; the retention floor
    # keeps the newest certified step restorable
    assert mgr.all_steps() == [2]
    assert _leaf(mgr.restore(2)["w"])[0] == 2.0


# ---- sharded certification ----

def test_sharded_fallback_certifies_and_refuses(tmp_path):
    path = str(tmp_path / "sharded")
    s0 = {"w": np.arange(4, dtype=np.float32)}
    s1 = {"w": np.arange(4, 8, dtype=np.float32)}
    save_sharded(s0, path, shard_id=0, num_shards=2, use_orbax=False)
    save_sharded(s1, path, shard_id=1, num_shards=2, use_orbax=False)
    assert os.path.exists(os.path.join(path, "shard_1.manifest.json"))
    out = load_sharded(path, shard_id=1, use_orbax=False)
    np.testing.assert_array_equal(_leaf(out["w"]), s1["w"])
    with pytest.raises(ValueError, match="pass shard_id"):
        load_sharded(path, use_orbax=False)

    # missing manifest → the whole set is uncertified
    os.rename(os.path.join(path, "shard_1.manifest.json"),
              os.path.join(path, "shard_1.manifest.bak"))
    with pytest.raises(ValueError, match="missing manifests"):
        load_sharded(path, shard_id=0, use_orbax=False)
    os.rename(os.path.join(path, "shard_1.manifest.bak"),
              os.path.join(path, "shard_1.manifest.json"))

    # torn shard data → CRC refusal even for the OTHER shard's load
    with open(os.path.join(path, "shard_0.pdckpt"), "r+b") as f:
        f.write(b"\x00\x01\x02\x03")
    with pytest.raises(ValueError, match="fails\\s+its manifest CRC"):
        load_sharded(path, shard_id=1, use_orbax=False)

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with pytest.raises(ValueError, match="no shard manifests"):
        load_sharded(empty, use_orbax=False)

    mixed = str(tmp_path / "mixed")
    save_sharded(s0, mixed, shard_id=0, num_shards=2, use_orbax=False)
    save_sharded(s1, mixed, shard_id=1, num_shards=3, use_orbax=False)
    with pytest.raises(ValueError, match="mismatched num_shards"):
        load_sharded(mixed, shard_id=0, use_orbax=False)


def test_rng_cursor_roundtrip():
    rs = np.random.RandomState(7)
    rs.randn(16)
    cur = rng_cursor(rs)
    expect = rs.randn(8)
    rs.randn(100)  # wander off
    restore_rng(rs, cur)
    np.testing.assert_array_equal(rs.randn(8), expect)


# ---- trainer integration ----

def _toy_trainer(ckpt, plan=None, **cfg):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rs = np.random.RandomState(3)
    x = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))

    def train_fn(_i):
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return ResilientTrainer(
        train_fn, ckpt,
        get_state=lambda: {"model": model.state_dict()},
        set_state=lambda s: model.set_state_dict(s["model"]),
        fault_plan=plan if plan is not None else FaultPlan.from_spec(""),
        config=ResilientConfig(**cfg))


def test_nan_rollback_served_from_ring(tmp_path):
    ckpt = AsyncCheckpointManager(str(tmp_path), max_to_keep=10)
    t = _toy_trainer(ckpt, plan=FaultPlan.from_spec("nan_loss@5"),
                     nan_policy="rollback", save_interval=2)
    summary = t.run(lambda i: i, num_steps=8)
    assert summary["completed_steps"] == 8
    rb = [e for e in summary["events"] if e["kind"] == "rollback"]
    assert rb and rb[0]["step"] == 4 and rb[0]["source"] == "ring"
    assert summary["checkpoint"]["snapshots"] >= 4
    ckpt.close()


def test_prom_ckpt_families_render(tmp_path):
    from paddle_tpu.obs.prom import TrainingMetrics
    mgr = AsyncCheckpointManager(str(tmp_path))
    mgr.snapshot(1, {"w": np.ones(4, np.float32)})
    mgr.wait_until_finished()
    text = TrainingMetrics(ckpt=mgr).render()
    assert "pdtpu_train_ckpt_snapshots_total 1" in text
    assert "pdtpu_train_ckpt_persisted_total 1" in text
    assert "pdtpu_train_ckpt_dropped_total 0" in text
    assert "pdtpu_train_ckpt_queue_depth 0" in text
    assert "pdtpu_train_ckpt_blocking_seconds_total" in text
    assert "pdtpu_train_ckpt_async_seconds_total" in text
    mgr.close()


def test_async_blocking_strictly_below_sync_at_equal_frequency(tmp_path):
    """Acceptance: at save_interval=1 over a ~2MB state, the async tier's
    blocking checkpoint seconds must sit strictly below the synchronous
    baseline's, with the ledger phases still tiling the wall."""
    state = {"w": np.random.randn(512, 1024).astype(np.float32)}

    def run_one(ckpt):
        t = ResilientTrainer(
            lambda _i: 0.5, ckpt,
            get_state=lambda: state,
            set_state=lambda s: None,
            fault_plan=FaultPlan.from_spec(""),
            config=ResilientConfig(save_interval=1),
            goodput=True)
        summary = t.run(lambda i: i, num_steps=6)
        assert summary["completed_steps"] == 6
        return summary["goodput"]

    sync_g = run_one(CheckpointManager(str(tmp_path / "sync"),
                                       max_to_keep=2, use_orbax=False))
    async_mgr = AsyncCheckpointManager(str(tmp_path / "async"),
                                       max_to_keep=2)
    async_g = run_one(async_mgr)
    async_mgr.close()
    assert async_g["checkpoint_blocking_seconds"] \
        < sync_g["checkpoint_blocking_seconds"]
    assert async_g["checkpoint_async_seconds"] > 0
    assert sync_g["checkpoint_async_seconds"] == 0
    # the writer thread's seconds are NOT a phase: booked phases + idle
    # must still tile the wall (idle is the clamped residual, so the sum
    # can only exceed wall if a phase double-booked)
    for g in (sync_g, async_g):
        booked = sum(g["phase_seconds"].values())
        assert booked <= g["wall_seconds"] * 1.05 + 1e-6


# ---- subprocess end-to-end (the fault matrix) ----

def _run_worker(workdir, mode="fast", faults=None, num_steps=8,
                snap_interval=2, wait=True):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["NUM_STEPS"] = str(num_steps)
    env["SNAP_INTERVAL"] = str(snap_interval)
    if faults:
        env[fault_injection.ENV_VAR] = faults
    else:
        env.pop(fault_injection.ENV_VAR, None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(FIXTURES, "async_ckpt_worker.py"),
         str(workdir), mode],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    if not wait:
        return proc
    out, err = proc.communicate(timeout=120)
    return proc.returncode, out, err


def _losses_by_step(workdir):
    by_step = {}
    with open(os.path.join(str(workdir), "losses.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            by_step.setdefault(rec["step"], []).append(rec["loss"])
    return by_step


def _assert_stitched_bit_identical(faulty_dir, clean_dir, num_steps):
    """Every recording of a step — across the killed + resumed processes,
    including rollback replays — must be bit-identical, and together they
    must reproduce the uninterrupted run exactly."""
    faulty = _losses_by_step(faulty_dir)
    clean = _losses_by_step(clean_dir)
    assert set(faulty) == set(range(num_steps)) == set(clean)
    for s in range(num_steps):
        assert len(set(faulty[s])) == 1, \
            f"step {s} diverged across kill/resume: {faulty[s]}"
        assert faulty[s][0] == clean[s][0], \
            f"step {s}: resumed {faulty[s][0]!r} != clean {clean[s][0]!r}"


@pytest.mark.fault_matrix
def test_kill_during_background_persist_exact_resume(tmp_path):
    """SIGKILL inside the writer thread while it persists snapshot 4:
    disk keeps step 2 (snapshot_interval=2 → ≤2 steps of work lost on
    disk), and the resumed trajectory is bit-identical to a clean run."""
    faulty, clean = tmp_path / "faulty", tmp_path / "clean"
    faulty.mkdir(), clean.mkdir()
    rc, _, err = _run_worker(faulty, faults="kill@4:persist")
    assert rc == 137, err[-3000:]
    mgr = CheckpointManager(str(faulty / "ckpt"), use_orbax=False)
    assert mgr.latest_step() == 2  # step 4's persist died before landing
    rc, _, err = _run_worker(faulty)
    assert rc == 0, err[-3000:]
    report = json.load(open(faulty / "report.json"))
    assert report["resumed_from"] == 2
    assert report["completed"] == 8
    rc, _, err = _run_worker(clean)
    assert rc == 0, err[-3000:]
    _assert_stitched_bit_identical(faulty, clean, 8)


@pytest.mark.fault_matrix
def test_kill_mid_background_save_leaves_tmp_and_resumes(tmp_path):
    """SIGKILL after the writer wrote step 4's tmp data but before any
    rename: the tear stays un-certified and invisible to restore."""
    work = tmp_path / "w"
    work.mkdir()
    rc, _, err = _run_worker(work, faults="kill@4:mid_save")
    assert rc == 137, err[-3000:]
    mgr = CheckpointManager(str(work / "ckpt"), use_orbax=False)
    assert os.path.exists(mgr._data_path(4) + ".tmp")  # the tear is real
    assert not os.path.exists(mgr._manifest_path(4))
    assert mgr.latest_step() == 2
    rc, _, err = _run_worker(work)
    assert rc == 0, err[-3000:]
    report = json.load(open(work / "report.json"))
    assert report["resumed_from"] == 2 and report["completed"] == 8


@pytest.mark.fault_matrix
def test_torn_write_quarantined_by_scrubber_on_resume(tmp_path):
    """ckpt_torn_write@8 corrupts the final checkpoint AFTER its manifest
    landed — certified-but-corrupt. The first run exits clean; the resume
    must scrub it into step_8.corrupt/, fall back to step 6, and still
    produce a bit-consistent trajectory."""
    work = tmp_path / "w"
    work.mkdir()
    rc, _, err = _run_worker(work, faults="ckpt_torn_write@8", num_steps=8)
    assert rc == 0, err[-3000:]  # the tear is silent at save time
    rc, _, err = _run_worker(work, num_steps=12)
    assert rc == 0, err[-3000:]
    report = json.load(open(work / "report.json"))
    (q,) = report["quarantined"]
    assert q["step"] == 8 and q["file"] == "step_8.pdckpt"
    assert "crc32 mismatch" in q["reason"]
    assert "ckpt_quarantined" in report["event_kinds"]
    assert report["resumed_from"] == 6  # newest CLEAN step, not 8
    assert report["completed"] == 12
    assert (work / "ckpt" / "step_8.corrupt" / "step_8.pdckpt").exists()
    # stitched consistency: the replayed steps 6..7 must re-produce the
    # first run's values bit-for-bit
    by_step = _losses_by_step(work)
    assert set(by_step) == set(range(12))
    for s, vals in by_step.items():
        assert len(set(vals)) == 1, f"step {s} diverged: {vals}"


@pytest.mark.fault_matrix
def test_sigterm_emergency_save_reconciles_with_flight_dump(tmp_path):
    """Preemption on the async tier: SIGTERM → boundary snapshot →
    emergency persist from the ring → marker + black-box dump. The dump's
    ckpt_emergency step must reconcile with the marker AND with the
    newest certified step on disk; the next run resumes there."""
    work = tmp_path / "w"
    work.mkdir()
    proc = _run_worker(work, mode="slow", num_steps=40, wait=False)
    progress = work / "progress"
    deadline = time.time() + 60
    while time.time() < deadline:
        if progress.exists() and len(progress.read_text().splitlines()) >= 3:
            break
        time.sleep(0.05)
    else:
        proc.kill()
        pytest.fail("worker made no progress")
    proc.send_signal(signal.SIGTERM)
    _, err = proc.communicate(timeout=60)
    assert proc.returncode == 143, err[-3000:]
    marker = json.load(open(work / "ckpt" / PREEMPT_MARKER))
    assert marker["resumable"] and marker["step"] >= 2
    step = marker["step"]
    mgr = CheckpointManager(str(work / "ckpt"), use_orbax=False)
    assert mgr.latest_step() == step and mgr.verify(step)
    dump = json.load(open(work / "ckpt" / f"pdtpu_flight_{proc.pid}.json"))
    assert dump["reason"] == "preempt"
    kinds = {}
    for e in dump["events"]:
        kinds.setdefault(e["kind"], []).append(e)
    assert kinds["ckpt_emergency"][-1]["step"] == step
    emergency_persists = [e for e in kinds["ckpt_persist"]
                          if e.get("emergency")]
    assert emergency_persists and emergency_persists[-1]["step"] == step
    assert "train_preempted" in kinds
    rc, _, err = _run_worker(work, num_steps=40)
    assert rc == 0, err[-3000:]
    report = json.load(open(work / "report.json"))
    assert report["resumed_from"] == step and report["completed"] == 40
    assert not os.path.exists(work / "ckpt" / PREEMPT_MARKER)
