"""Training goodput ledger (ISSUE 10): phase attribution that tiles the
trainer's wall clock, live MFU sharing bench.py's analytic-FLOPs
helpers, the recompile sentinel (jax.monitoring + jit-cache fallback),
HBM telemetry + OOM forensics, and the rollback-storm fault-matrix
scenario proving a faulted run books rollback_waste, drops goodput, and
leaves a black-box dump the postmortem CLI can filter to `train_*`.

Ledger unit tests run on an injected fake clock, so every attribution
number is exact, not approximate."""
import json
import logging
import math
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu import obs
from paddle_tpu.obs.goodput import (
    PHASES, GoodputLedger, HBMTelemetry, RecompileSentinel, oom_forensics)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "flight_recorder.py")


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _tiles(snap, tol=1e-9):
    return abs(sum(snap["phase_seconds"].values())
               - snap["wall_seconds"]) <= tol


# ---- ledger attribution on a fake clock ----

def test_phase_order_matches_exclusive_set():
    assert PHASES == ("compute", "rollback_waste", "data_wait", "h2d",
                      "compile", "checkpoint", "idle")


def test_measure_books_self_time_and_idle_is_residual():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start()
    with led.measure("compute"):
        clk.tick(2.0)
    clk.tick(0.5)                       # unbooked -> idle
    with led.measure("data_wait"):
        clk.tick(0.25)
    snap = led.snapshot()
    assert snap["wall_seconds"] == pytest.approx(2.75)
    assert snap["phase_seconds"]["compute"] == pytest.approx(2.0)
    assert snap["phase_seconds"]["data_wait"] == pytest.approx(0.25)
    assert snap["phase_seconds"]["idle"] == pytest.approx(0.5)
    assert _tiles(snap)
    assert snap["goodput"] == pytest.approx(2.0 / 2.75)


def test_nested_measure_books_only_self_time():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.measure("compute"):        # first measure auto-starts
        clk.tick(1.0)
        with led.measure("h2d"):
            clk.tick(3.0)
        clk.tick(0.5)
    snap = led.snapshot()
    assert snap["phase_seconds"]["compute"] == pytest.approx(1.5)
    assert snap["phase_seconds"]["h2d"] == pytest.approx(3.0)
    assert snap["phase_seconds"]["idle"] == 0.0
    assert _tiles(snap)


def test_book_inside_measure_shrinks_enclosing_frame():
    # the sentinel's compile callback fires while the compute measure is
    # open: compile seconds must come OUT of compute, not double-count
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.measure("compute"):
        clk.tick(2.0)
        led.book("compile", 0.75)
    snap = led.snapshot()
    assert snap["phase_seconds"]["compute"] == pytest.approx(1.25)
    assert snap["phase_seconds"]["compile"] == pytest.approx(0.75)
    assert _tiles(snap)


def test_book_outside_any_measure_still_tiles():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start()
    clk.tick(1.0)
    led.book("checkpoint", 0.4)         # no open frame: plain attribution
    snap = led.snapshot()
    assert snap["phase_seconds"]["checkpoint"] == pytest.approx(0.4)
    assert snap["phase_seconds"]["idle"] == pytest.approx(0.6)
    assert _tiles(snap)


def test_overbooked_clock_clamps_never_negative():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start()
    with led.measure("compute"):
        clk.tick(0.1)
        led.book("compile", 5.0)        # callback over-reports
    snap = led.snapshot()
    assert snap["phase_seconds"]["compute"] == 0.0   # clamped, not -4.9
    assert snap["phase_seconds"]["idle"] == 0.0      # residual clamped too
    assert all(v >= 0.0 for v in snap["phase_seconds"].values())


def test_snapshot_before_start_is_zero():
    led = GoodputLedger(clock=FakeClock())
    snap = led.snapshot()
    assert snap["wall_seconds"] == 0.0 and snap["goodput"] == 0.0
    assert snap["mfu"] is None


def test_mfu_requires_flops_and_productive_steps():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start()
    with led.measure("compute"):
        clk.tick(2.0)
    assert led.snapshot()["mfu"] is None          # no flops registered
    led.set_flops(1e9, 1e12)
    assert led.snapshot()["mfu"] is None          # no productive steps
    led.add_steps(4, productive=False)
    assert led.snapshot()["mfu"] is None          # waste isn't MFU
    led.add_steps(10)
    snap = led.snapshot()
    assert snap["mfu"] == pytest.approx(1e9 * 10 / 2.0 / 1e12)
    assert snap["productive_steps"] == 10 and snap["wasted_steps"] == 4


# ---- recompile sentinel (unit, no jax needed) ----

def test_sentinel_warmup_then_recompiles_and_storm_warning(caplog):
    obs.flight_recorder().clear()
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.start()
    sen = RecompileSentinel(led, storm_threshold=2)
    sen.on_compile(1.5)                 # warmup: counted, not a recompile
    assert sen.compiles == 1 and sen.recompiles == 0
    sen.mark_warm()
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.goodput"):
        sen.on_compile(0.5)
        assert not any("recompile storm" in r.message
                       for r in caplog.records)
        sen.on_compile(0.25)            # hits threshold -> warn once
        sen.on_compile(0.25)
    storms = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storms) == 1
    snap = sen.snapshot()
    assert snap == {"compiles": 4, "recompiles": 3,
                    "compile_seconds": pytest.approx(2.5)}
    # compile seconds booked to the ledger
    assert led.snapshot()["phase_seconds"]["compile"] == pytest.approx(2.5)
    # every post-warm compile dropped a flight event; the storm one is
    # flagged
    ev = [e for e in obs.flight_recorder().snapshot()["events"]
          if e["kind"] == "train_recompile"]
    assert [e["recompiles"] for e in ev] == [1, 2, 3]
    assert [e["storm"] for e in ev] == [False, True, False]


def test_sentinel_rejects_bad_threshold():
    with pytest.raises(ValueError):
        RecompileSentinel(storm_threshold=0)


def test_sentinel_jit_cache_fallback_counts_build_misses():
    from paddle_tpu.utils.jit_cache import JitLRUCache
    sen = RecompileSentinel().install(source="jit_cache")
    assert sen.installed == "jit_cache"
    try:
        cache = JitLRUCache(4, name="goodput-test")
        cache.get_or_build(("a",), lambda: object())   # miss -> compile
        cache.get_or_build(("a",), lambda: object())   # hit -> nothing
        cache.get_or_build(("b",), lambda: object())   # miss
        assert sen.compiles == 2
        sen.mark_warm()
        cache.get_or_build(("c",), lambda: object())
        assert sen.recompiles == 1
    finally:
        sen.uninstall()
    cache.get_or_build(("d",), lambda: object())       # detached: ignored
    assert sen.compiles == 3 and sen.installed is None


def test_sentinel_install_is_idempotent_and_uninstall_detaches():
    s1 = RecompileSentinel().install(source="jit_cache")
    assert s1.install(source="jit_cache") is s1        # second no-op
    s1.uninstall()
    s1.uninstall()                                     # idempotent


# ---- recompile sentinel against real jax (acceptance criterion) ----

def test_stable_shapes_hold_recompile_count_but_churn_raises_it(caplog):
    import jax
    import jax.numpy as jnp

    obs.flight_recorder().clear()
    sen = RecompileSentinel(storm_threshold=2).install()

    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    try:
        f(jnp.ones((4,))).block_until_ready()          # warmup compile
        assert sen.compiles >= 1
        sen.mark_warm()
        baseline = sen.recompiles
        for _ in range(5):                             # stable shapes:
            f(jnp.ones((4,))).block_until_ready()      # cache hits only
        assert sen.recompiles == baseline, \
            "stable-shape loop must stay at its post-warmup count"
        with caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.goodput"):
            for n in (5, 6, 7):                        # shape churn
                f(jnp.ones((n,))).block_until_ready()
        assert sen.recompiles >= baseline + 3
        assert any("recompile storm" in r.message for r in caplog.records)
        kinds = [e["kind"] for e in
                 obs.flight_recorder().snapshot()["events"]]
        assert "train_recompile" in kinds
    finally:
        sen.uninstall()
    before = sen.compiles
    f(jnp.ones((9,))).block_until_ready()              # detached: ignored
    assert sen.compiles == before


# ---- HBM telemetry + OOM forensics ----

def test_hbm_sample_and_attribution_with_fake_stats():
    hbm = HBMTelemetry(stats_fn=lambda: {
        "bytes_in_use": 1 << 30, "peak_bytes_in_use": 2 << 30,
        "bytes_limit": 16 << 30, "num_allocs": 7})
    hbm.attribute("params", 4096)
    hbm.attribute("opt_state", 8192)
    snap = hbm.snapshot()
    assert snap["available"] is True
    assert snap["bytes_in_use"] == 1 << 30
    assert snap["peak_bytes_in_use"] == 2 << 30
    assert snap["bytes_limit"] == 16 << 30
    assert "num_allocs" not in snap                    # gauge allowlist
    assert snap["attributed"] == {"params": 4096, "opt_state": 8192}


def test_hbm_unavailable_backend_is_graceful():
    # CPU jax returns None from memory_stats(); a raising fn degrades the
    # same way
    assert HBMTelemetry(stats_fn=lambda: None).sample() == {
        "available": False}
    def boom():
        raise RuntimeError("no allocator stats")
    assert HBMTelemetry(stats_fn=boom).sample() == {"available": False}
    # the default stats_fn on the forced-CPU test backend must not raise
    assert HBMTelemetry().sample()["available"] is False


def test_tree_nbytes_walks_nests_and_tensor_wrappers():
    class Wrapped:                       # core.Tensor-style .data holder
        data = np.zeros((4, 4), np.float32)
    tree = {"a": np.zeros(8, np.float32),
            "b": [np.zeros(2, np.int64), (np.zeros(3, np.int8),)],
            "c": Wrapped(), "d": "not-an-array"}
    assert HBMTelemetry.tree_nbytes(tree) == 8 * 4 + 2 * 8 + 3 + 64


def test_oom_forensics_dumps_watermarks_and_attribution(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    obs.flight_recorder().clear()
    hbm = HBMTelemetry(stats_fn=lambda: {
        "bytes_in_use": 900, "peak_bytes_in_use": 1000,
        "bytes_limit": 1000})
    hbm.attribute("params", 600)
    # not an OOM: no event, no dump
    assert oom_forensics(ValueError("shape mismatch"), hbm) is None
    assert not list(tmp_path.iterdir())
    # XLA's RESOURCE_EXHAUSTED surfaces as a generic RuntimeError text
    exc = RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes")
    path = oom_forensics(exc, hbm)
    assert path is not None and os.path.exists(path)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "oom"
    oom = [e for e in doc["events"] if e["kind"] == "train_oom"]
    assert len(oom) == 1
    assert oom[0]["hbm_peak_bytes_in_use"] == 1000
    assert oom[0]["attr_params_bytes"] == 600
    assert "RESOURCE_EXHAUSTED" in oom[0]["error"]


# ---- ResilientTrainer integration ----

class _Toy:
    """Step fn with a fixed per-step cost so phase shares are predictable."""

    def __init__(self, step_cost=0.0, fail=None):
        self.w = 0.0
        self.trained = []
        self.step_cost = step_cost
        # step -> list of exceptions, one consumed per attempt
        self.fail = {k: list(v) for k, v in (fail or {}).items()}

    def train_fn(self, step):
        if self.step_cost:
            time.sleep(self.step_cost)
        if self.fail.get(step):
            raise self.fail[step].pop(0)
        self.w += 1.0
        self.trained.append(step)
        return 1.0 / (step + 1)

    def trainer(self, tmp_path, name="ckpt", plan=None, goodput=True, **cfg):
        from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                      ResilientTrainer)
        from paddle_tpu.utils.fault_injection import FaultPlan
        return ResilientTrainer(
            self.train_fn, str(tmp_path / name),
            get_state=lambda: {"w": self.w},
            set_state=lambda s: setattr(self, "w", s["w"]),
            config=ResilientConfig(**cfg),
            fault_plan=plan if plan is not None else FaultPlan(),
            use_orbax=False, goodput=goodput)


def test_disabled_goodput_leaves_every_hook_at_none(tmp_path):
    toy = _Toy()
    t = toy.trainer(tmp_path, goodput=False)
    assert t.ledger is None and t.sentinel is None and t.hbm is None
    assert t.worker.ledger is None
    summary = t.run(lambda i: i, num_steps=2)
    assert "goodput" not in summary


def test_faulted_run_reconciles_phases_against_wall_clock(tmp_path):
    """Acceptance: on a deterministic run with injected rollback +
    checkpoint + data-stall faults, phase seconds tile measured wall
    clock within 1% and the waste phases are actually populated."""
    from paddle_tpu.utils.fault_injection import FaultPlan

    toy = _Toy(step_cost=0.02)
    # raise@2 twice with max_step_retries=1: one backoff retry (booked as
    # rollback_waste), then rollback to the step-2 checkpoint and a
    # below-watermark replay would occur had we rolled further back; the
    # nan at 5 escalates straight to rollback (policy) replaying 4..5
    plan = FaultPlan.from_spec("raise@2:OSError;raise@2:OSError;nan_loss@5")
    t = toy.trainer(tmp_path, plan=plan, nan_policy="rollback",
                    max_rollbacks=3, max_step_retries=1,
                    retry_backoff=0.03, save_interval=2)

    def batch_fn(i):
        time.sleep(0.005)               # a stalled input pipeline
        return i

    summary = t.run(batch_fn, num_steps=8)
    assert summary["completed_steps"] == 8
    assert summary["rollbacks"] >= 2 and summary["retries"] >= 1
    snap = summary["goodput"]
    booked = sum(snap["phase_seconds"].values())
    assert booked == pytest.approx(snap["wall_seconds"],
                                   rel=0.01, abs=1e-4)
    ph = snap["phase_seconds"]
    assert ph["compute"] > 0.0
    assert ph["data_wait"] >= 8 * 0.005 * 0.5   # batch_fn stalls booked
    assert ph["checkpoint"] > 0.0               # periodic saves + restores
    # rollback_waste: the backoff sleep plus the step-4 replay after the
    # nan rollback (below the watermark -> device time is waste)
    assert ph["rollback_waste"] >= 0.03 * 0.5
    assert snap["wasted_steps"] >= 1
    # 8 completed + the poisoned step-5 execution: it ran ABOVE the
    # watermark (the trainer can't know a loss is bad until it reads it),
    # so only the below-watermark step-4 replay is booked as waste
    assert snap["productive_steps"] == 9
    assert 0.0 < snap["goodput"] < 1.0


def test_live_mfu_matches_offline_formula_on_clean_run(tmp_path):
    """Acceptance: live MFU (ledger) and the offline number computed the
    way bench.py computes it — same obs.flops helpers, wall measured
    around the run — agree within 5%."""
    from paddle_tpu.obs.flops import peak_flops, train_flops_per_step

    flops_per_step = train_flops_per_step(1e6, tokens_per_step=64)
    peak = peak_flops("cpu", backend="cpu")
    toy = _Toy(step_cost=0.03)
    t = toy.trainer(tmp_path, save_interval=100)
    t.ledger.set_flops(flops_per_step, peak)
    t0 = time.perf_counter()
    summary = t.run(lambda i: i, num_steps=10)
    wall = time.perf_counter() - t0
    live = summary["goodput"]["mfu"]
    offline = flops_per_step * 10 / wall / peak
    assert live is not None
    assert live == pytest.approx(offline, rel=0.05)
    # and the exporter scrapes it as a finite gauge (the scrape happens
    # a beat later, so its wall is a hair larger: compare loosely)
    flat = obs.parse_exposition(t.metrics.render())
    assert flat["pdtpu_train_mfu"] == pytest.approx(live, rel=0.05)
    assert flat["pdtpu_train_goodput"] == pytest.approx(
        summary["goodput"]["goodput"], rel=0.05)


# ---- the fault-matrix scenario (tools/check_fault_matrix.py) ----

@pytest.mark.fault_matrix
def test_rollback_storm_books_waste_and_dump_is_filterable(tmp_path,
                                                           monkeypatch):
    """Rollback storm: a run hit by an OOM step + shape churn books
    rollback_waste, its goodput drops below the clean run's, and the
    black-box dump (written at the OOM, before recovery even starts)
    already carries the train_recompile/train_oom vocabulary — which the
    postmortem CLI isolates with --kind 'train_*'."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    obs.flight_recorder().clear()
    obs.flight_recorder().record("unit_noise", n=1)    # non-train kind

    # clean reference run: stable shapes, no faults
    clean = _Toy(step_cost=0.02)
    sc = clean.trainer(tmp_path, name="ckpt_clean").run(
        lambda i: i, num_steps=6)
    clean_goodput = sc["goodput"]["goodput"]
    assert sc["goodput"]["phase_seconds"]["rollback_waste"] == 0.0

    # storm run: every step jits a NEW shape (churn), and step 2 dies
    # with an XLA OOM -> retries (backoff waste) -> rollback (replay
    # waste)
    @jax.jit
    def probe(x):
        return (x * 2.0).sum()

    oom = RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9663676416 bytes")
    # two OOMs at step 2: the first retry (backoff -> rollback_waste)
    # also fails, exhausting max_step_retries=1 -> rollback
    storm = _Toy(step_cost=0.02, fail={2: [oom, oom]})
    orig = storm.train_fn

    def churny(step):
        probe(jnp.ones((step + 1,))).block_until_ready()
        return orig(step)

    storm.train_fn = churny
    t = storm.trainer(tmp_path, name="ckpt_storm", max_step_retries=1,
                      retry_backoff=0.05, max_rollbacks=2, save_interval=2)
    summary = t.run(lambda i: i, num_steps=6)
    assert summary["completed_steps"] == 6
    assert summary["rollbacks"] >= 1

    snap = summary["goodput"]
    assert snap["phase_seconds"]["rollback_waste"] > 0.0
    assert snap["goodput"] < clean_goodput
    assert t.sentinel.recompiles >= 1                  # churn was seen
    assert any(e["kind"] == "step_error"
               and "RESOURCE_EXHAUSTED" in e["error"]
               for e in summary["events"])

    # the OOM dumped the ring atomically at failure time
    dump_path = tmp_path / f"pdtpu_flight_{os.getpid()}.json"
    assert dump_path.exists(), "OOM must dump the flight ring"
    assert not (tmp_path / (dump_path.name + ".tmp")).exists()
    doc = json.loads(dump_path.read_text())
    assert doc["reason"] == "oom"
    kinds = [e["kind"] for e in doc["events"]]
    assert "train_oom" in kinds
    assert "train_recompile" in kinds                  # churn preceded it
    assert "unit_noise" in kinds                       # ring is unfiltered

    # postmortem CLI: --kind 'train_*' isolates the trainer vocabulary
    r = subprocess.run(
        [sys.executable, CLI, str(dump_path), "--kind", "train_*"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "train_oom" in r.stdout and "train_recompile" in r.stdout
    assert "unit_noise" not in r.stdout
    assert "RESOURCE_EXHAUSTED" in r.stdout            # info survives
