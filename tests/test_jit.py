"""jit path tests: TrainStep/to_static parity with eager (the reference's
dygraph-vs-static parity suite analog), incl. regression tests for traced RNG,
buffer carry, and grad clip on the compiled path."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def test_to_static_matches_eager():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model.eval()
    x = paddle.randn([3, 4])
    eager = model(x).numpy()
    static = paddle.jit.to_static(model)(x).numpy()
    np.testing.assert_allclose(static, eager, rtol=1e-5, atol=1e-6)


def test_to_static_forwards_kwargs():
    class M(nn.Layer):
        def forward(self, x, scale=None):
            if scale is not None:
                return x * scale
            return x

    m = M()
    x = paddle.ones([2, 2])
    out = paddle.jit.to_static(m)(x, scale=paddle.to_tensor(3.0))
    np.testing.assert_allclose(out.numpy(), np.full((2, 2), 3.0))


def test_train_step_converges_and_matches_eager_rule():
    paddle.seed(7)
    model = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda out, y: nn.functional.mse_loss(out, y), opt)
    x = paddle.randn([16, 4])
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = paddle.to_tensor(x.numpy() @ w_true)
    losses = [float(step(x, y).item()) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.1


def test_train_step_dropout_mask_varies_per_step():
    # regression: the mask must NOT be baked into the compiled executable
    class Drop(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(64, 64)
            self.drop = nn.Dropout(0.5)

        def forward(self, x):
            return self.drop(self.lin(x))

    model = Drop()
    opt = optimizer.SGD(learning_rate=0.0, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, lambda o, y: (o * y).sum(), opt)
    x = paddle.ones([1, 64])
    y = paddle.ones([1, 64])
    # lr=0 → params frozen; dropout pattern shows in grads? Instead check loss:
    l1 = float(step(x, y).item())
    l2 = float(step(x, y).item())
    l3 = float(step(x, y).item())
    # identical inputs & params, only the dropout mask differs
    assert not (l1 == l2 == l3), "dropout mask is constant across jit steps"


def test_train_step_updates_batchnorm_running_stats():
    # regression: buffer updates must survive the traced step
    model = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda o, y: nn.functional.mse_loss(o, y), opt)
    before = model[1]._mean.numpy().copy()
    x = paddle.randn([32, 4]) + 5.0
    y = paddle.randn([32, 8])
    step(x, y)
    after = model[1]._mean.numpy()
    assert not np.allclose(before, after), "running mean did not update"


def test_train_step_applies_grad_clip():
    w0 = 1.0
    model = nn.Linear(1, 1, bias_attr=False)
    model.weight.set_value(np.array([[w0]], np.float32))
    clip = nn.ClipGradByGlobalNorm(0.5)
    opt = optimizer.SGD(learning_rate=1.0, parameters=model.parameters(),
                        grad_clip=clip)
    step = paddle.jit.TrainStep(model, lambda o, y: (o * 10.0).sum(), opt)
    x = paddle.ones([1, 1])
    step(x, paddle.ones([1, 1]))
    # raw grad is 10; clipped global-norm to 0.5 → w = 1 - 0.5
    np.testing.assert_allclose(model.weight.numpy(), [[0.5]], rtol=1e-5)


def test_grad_wrt_intermediate():
    # regression: paddle.grad must work for non-leaf inputs
    x = paddle.to_tensor([2.0], stop_gradient=False)
    x2 = x * 2
    y = (x2 * x2).sum()
    (g,) = paddle.grad([y], [x2])
    np.testing.assert_allclose(g.numpy(), [8.0])


def test_grad_does_not_pollute_other_leaves():
    # regression: paddle.grad must not touch .grad of unrelated params
    w = paddle.core.tensor.Parameter(np.array([3.0], np.float32))
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (w * x).sum()
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), [3.0])
    assert w.grad is None, "paddle.grad polluted parameter .grad"
