"""Prefix-sharing radix KV cache + multi-tenant serving (ISSUE 8):
shared-block-pool accounting (attach/refcount/COW/block ledger),
refcount-aware defrag, radix lookup/insert/LRU-eviction, the SimClock
acceptance proof (N shared-prefix requests cost ~1 prefill with streams
bit-identical to cold greedy generate()), the fault-matrix scenarios
(poisoned sibling quarantined without corrupting shared blocks; eviction
under pressure never reclaims a block with live readers), tenant-fair
scheduling + quotas, and the X-Tenant-Id HTTP surface.

Module is auto-marked `prefix` (and `llm`) via tests/conftest.py."""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


def _pool(num_slots=4, block_len=4, n_blocks=2):
    import jax.numpy as jnp
    from paddle_tpu.serving.llm import SlotPagedKVPool

    def init_cache(b, max_len):
        return [(jnp.zeros((b, 2, max_len, 3), jnp.float32),
                 jnp.zeros((b, 2, max_len, 3), jnp.float32))]

    return SlotPagedKVPool(init_cache, num_slots, block_len, n_blocks)


def _engine(gpt_tiny, clock, plan=None, **cfg_kw):
    from paddle_tpu import serving
    kw = dict(num_slots=4, block_len=8, n_blocks=6, prefill_chunk=16)
    kw.update(cfg_kw)
    return serving.LLMEngine(gpt_tiny, serving.LLMEngineConfig(**kw),
                             clock=clock, fault_plan=plan)


def _drain_all(eng):
    while eng.has_work():
        eng.pump()


# ---- shared block pool (host-side accounting, fake cache fn) ----

def test_attach_refcount_and_block_ledger():
    p = _pool()                       # 4 slots x 2 blocks of 4 tokens
    s0 = p.allocate(8)
    p.set_length(s0, 8)               # claims 2 own pages
    assert p.stats["blocks_allocated"] == 2 and p.blocks_active() == 2
    p.register_cached(0)
    p.register_cached(1)
    p.free(s0)                        # ownership transfers to the cache
    assert p.blocks_cached() == 2 and p.stats["blocks_freed"] == 0
    p.check_balance()
    s1 = p.allocate(8)
    assert s1 == 1                    # row 0 is pinned by cached pages
    p.attach_blocks(s1, [0, 1])
    assert p.refcount == {0: 1, 1: 1}
    p.set_length(s1, 8)               # fully covered by attached pages
    assert p.stats["blocks_allocated"] == 2   # no new own pages
    assert p.block_table[s1] == [0, 1]
    with pytest.raises(ValueError, match="live reader"):
        p.release_cached(0)           # eviction refused under readers
    p.free(s1)
    assert p.refcount == {}
    p.check_balance()
    p.release_cached(0)
    p.release_cached(1)
    assert p.stats["blocks_freed"] == 2 and p.blocks_cached() == 0
    p.check_balance()
    with pytest.raises(ValueError, match="not cache-registered"):
        s2 = p.allocate(4)
        p.attach_blocks(s2, [0])      # uncached pages cannot be shared


def test_cow_copy_moves_one_block_between_rows():
    import jax.numpy as jnp
    p = _pool(num_slots=2, block_len=4, n_blocks=2)
    s0 = p.allocate(8)
    k, v = p.slabs[0]
    # fill slot 0's SECOND block (cols 4..8) with a recognizable value
    p.slabs[0] = (k.at[s0, :, 4:8].set(7.0), v.at[s0, :, 4:8].set(3.0))
    s1 = p.allocate(4)
    p.cow_copy(s0 * 2 + 1, s1)        # page 1 = slot0/block1
    k, v = p.slabs[0]
    assert float(jnp.abs(k[s1, :, 4:8] - 7.0).max()) == 0.0
    assert float(jnp.abs(v[s1, :, 4:8] - 3.0).max()) == 0.0
    assert float(jnp.abs(k[s1, :, :4]).max()) == 0.0   # only that block
    assert p.stats["cow_copies"] == 1


def test_defrag_is_refcount_aware_at_page_granularity():
    import jax.numpy as jnp
    p = _pool(num_slots=2, block_len=4, n_blocks=2)
    s = p.allocate(8)
    k, v = p.slabs[0]
    p.slabs[0] = (k.at[s].set(7.0), v.at[s].set(7.0))
    p.set_length(s, 8)
    p.register_cached(s * 2)          # pin the FIRST page only
    p.free(s)
    assert p.dirty_blocks() == 1      # second page is scrubable
    assert p.defrag() == 1
    k, _ = p.slabs[0]
    assert float(jnp.abs(k[s, :, :4] - 7.0).max()) == 0.0   # cached: intact
    assert float(jnp.abs(k[s, :, 4:8]).max()) == 0.0        # scrubbed
    p.release_cached(s * 2)           # unpin -> row scrubable again
    assert p.dirty_blocks() == 2
    assert p.defrag() == 2
    assert float(jnp.abs(p.slabs[0][0]).sum()) == 0.0
    p.check_balance()


def test_allocate_skips_pinned_rows_and_calls_pressure_hook():
    from paddle_tpu.serving.llm import SlotsExhaustedError
    p = _pool(num_slots=1, block_len=4, n_blocks=2)
    s = p.allocate(4)
    p.set_length(s, 4)
    p.register_cached(0)
    p.free(s)
    with pytest.raises(SlotsExhaustedError, match="pinned"):
        p.allocate(4)                 # only row is pinned, no hook
    calls = []

    def pressure():
        calls.append(1)
        p.release_cached(0)
        return 1

    p.on_pressure = pressure
    assert p.allocate(4) == 0         # hook evicted, row reusable
    assert calls == [1]
    p.check_balance()


# ---- radix index (fake pool, no model) ----

def test_radix_lookup_insert_tail_and_tenant_namespacing():
    from paddle_tpu.serving.llm import PrefixCache
    p = _pool(num_slots=4, block_len=4, n_blocks=4)
    cache = PrefixCache(p)
    s = p.allocate(10)
    toks = np.arange(100, 110, dtype=np.int32)    # 2 full blocks + 2 tail
    p.set_length(s, 10)
    cache.insert("a", toks, s, [])
    assert cache.cached_blocks("a") == 3          # 2 nodes + 1 tail page
    plan = cache.acquire("a", toks, max_tokens=9)
    assert plan.pages == [s * 4, s * 4 + 1]
    assert plan.tail_page == s * 4 + 2 and plan.tail_len == 1   # cap 9
    assert plan.attach_len == 9
    assert p.refcount[s * 4] == 1 and p.refcount[s * 4 + 2] == 1
    cache.release_tail(plan)
    assert s * 4 + 2 not in p.refcount            # tail ref was transient
    for pg in plan.pages:
        p.release_block(pg)
    # divergent suffix: only the common full blocks match
    other = np.concatenate([toks[:8], [1, 2, 3, 4]]).astype(np.int32)
    plan2 = cache.acquire("a", other, max_tokens=11)
    assert plan2.attach_len == 8 and plan2.tail_page is None
    for pg in plan2.pages:
        p.release_block(pg)
    # tenants never share KV: same tokens, different namespace -> miss
    plan3 = cache.acquire("b", toks, max_tokens=9)
    assert plan3.attach_len == 0 and not plan3.pages
    assert cache.hit_rate("b") == 0.0 and cache.hit_rate("a") > 0.0


def test_lru_eviction_under_pressure_frees_coldest_first():
    from paddle_tpu.serving.llm import PrefixCache
    p = _pool(num_slots=2, block_len=4, n_blocks=2)
    cache = PrefixCache(p)            # wires itself as on_pressure
    s0 = p.allocate(8)
    old = np.arange(0, 8, dtype=np.int32)
    p.set_length(s0, 8)
    cache.insert("t", old, s0, [])
    p.free(s0)
    s1 = p.allocate(8)
    assert s1 == 1
    new = np.arange(100, 108, dtype=np.int32)
    p.set_length(s1, 8)
    cache.insert("t", new, s1, [])
    p.free(s1)
    # every row pinned; allocation pressure must evict the LRU ('old')
    # entry's pages and leave the recently-touched 'new' chain alone
    s2 = p.allocate(8)
    assert s2 == 0                    # old chain lived in row 0
    assert cache.stats["evictions"] == 2
    assert cache.acquire("t", old, max_tokens=7).attach_len == 0
    plan = cache.acquire("t", new, max_tokens=7)
    assert plan.attach_len == 7       # survivor chain intact (1 block+tail)
    p.check_balance()


# ---- SimClock acceptance: N shared-prefix requests ~ 1 prefill ----

def test_shared_prefix_requests_cost_one_prefill_bit_identically(gpt_tiny):
    """8 requests sharing a 32-token prefix (4 blocks) with unique 8-token
    suffixes: the first pays a full 40-token prefill, every later one
    attaches the cached blocks and prefills exactly its 8-token suffix —
    total prefilled tokens 40 + 7*8 = 96 vs 320 cold — and every stream
    is bit-identical to cold-path batch-locked greedy generate()."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate

    rng = np.random.RandomState(7)
    shared = rng.randint(1, 500, size=32).astype(np.int32)
    prompts = [np.concatenate(
        [shared, rng.randint(1, 500, size=8).astype(np.int32)])
        for _ in range(8)]
    NEW = 4
    ref = np.asarray(generate(gpt_tiny, np.stack(prompts),
                              max_new_tokens=NEW).numpy())[:, 40:]

    clock = serving.SimClock()
    eng = _engine(gpt_tiny, clock)
    h0 = eng.submit(prompts[0], max_new_tokens=NEW)
    _drain_all(eng)                   # donor completes -> prefix cached
    assert eng.prefill_tokens == 40
    handles = [eng.submit(pr, max_new_tokens=NEW) for pr in prompts[1:]]
    _drain_all(eng)
    # ~1 prefill total: donor's 40 tokens + 7 x 8-token suffixes
    assert eng.prefill_tokens == 40 + 7 * 8
    assert eng.prefill_tokens <= 0.35 * sum(len(p) for p in prompts)
    for h, r in zip([h0] + handles, ref):
        assert np.array_equal(h.result(timeout=0), r)
    snap = eng.metrics.snapshot()
    assert snap["prefix_hits"] == 7 and snap["prefix_misses"] == 1
    assert snap["prefix_hit_tokens"] == 7 * 32
    assert snap["prefix_hit_rate"] == pytest.approx(224 / 320)
    assert snap["cached_blocks"] >= 5
    eng.pool.check_balance()
    eng.stop()


def test_full_hit_duplicate_prompt_prefills_one_token(gpt_tiny):
    """An exact-duplicate prompt can't skip ALL prefill (the last token's
    step produces the first output logits): the cache attaches 4 full
    blocks, COWs 7 tokens of the 5th into the slot's own page, and the
    engine prefills exactly 1 token — TTFT = one chunk-wide step — with
    the warm stream bit-identical to the cold one."""
    from paddle_tpu import serving

    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 500, size=40).astype(np.int32)
    eng = _engine(gpt_tiny, serving.SimClock())
    cold = eng.submit(prompt, max_new_tokens=4)
    _drain_all(eng)
    base = eng.prefill_tokens
    warm = eng.submit(prompt, max_new_tokens=4)
    _drain_all(eng)
    assert eng.prefill_tokens - base == 1
    assert eng.pool.stats["cow_copies"] == 1
    assert np.array_equal(warm.result(timeout=0), cold.result(timeout=0))
    eng.pool.check_balance()
    eng.stop()


# ---- fault matrix (ISSUE 8 scenarios) ----

@pytest.mark.fault_matrix
def test_poisoned_sibling_quarantined_without_corrupting_shared_blocks(
        gpt_tiny):
    """Two requests attach the same cached prefix; one is poisoned
    mid-decode. The poisoned request is quarantined, the sibling's FULL
    stream through the shared blocks stays bit-identical to a fault-free
    run, the shared pages survive (no eviction, donor KV intact — a
    LATER request still attaches them bit-identically), and both the
    slot and block ledgers balance."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate
    from paddle_tpu.utils.fault_injection import FaultPlan

    rng = np.random.RandomState(3)
    shared = rng.randint(1, 500, size=32).astype(np.int32)
    mk = lambda: np.concatenate(  # noqa: E731
        [shared, rng.randint(1, 500, size=8).astype(np.int32)])
    donor_p, surv_p, pois_p, late_p = mk(), mk(), mk(), mk()
    ref_surv = np.asarray(generate(gpt_tiny, surv_p[None, :],
                                   max_new_tokens=6).numpy())[0, 40:]
    ref_late = np.asarray(generate(gpt_tiny, late_p[None, :],
                                   max_new_tokens=6).numpy())[0, 40:]

    plan = FaultPlan.from_spec("poison_request@2:decode")
    eng = _engine(gpt_tiny, serving.SimClock(), plan=plan)
    eng.submit(donor_p, max_new_tokens=2)          # idx 0: seeds the cache
    _drain_all(eng)
    survivor = eng.submit(surv_p, max_new_tokens=6)   # idx 1, attaches
    poisoned = eng.submit(pois_p, max_new_tokens=6)   # idx 2, attaches
    _drain_all(eng)
    with pytest.raises(serving.DispatchFailedError) as exc:
        poisoned.result(timeout=0)
    assert exc.value.reason == "poisoned"
    assert np.array_equal(survivor.result(timeout=0), ref_surv)
    # quarantine freed the poisoned slot's refcounts but evicted nothing
    assert eng.prefix_cache.stats["evictions"] == 0
    assert not eng.pool.refcount                   # all readers released
    late = eng.submit(late_p, max_new_tokens=6)    # idx 3: cache still hot
    _drain_all(eng)
    assert np.array_equal(late.result(timeout=0), ref_late)
    snap = eng.metrics.snapshot()
    assert snap["quarantined"] == 1 and snap["prefix_hits"] == 3
    assert snap["submitted"] == (snap["completed"] + snap["rejected"]
                                 + snap["expired"] + snap["failed"])
    eng.pool.check_balance()
    assert eng.pool.active_slots() == 0
    eng.stop()


@pytest.mark.fault_matrix
def test_eviction_under_pressure_never_reclaims_live_readers(gpt_tiny):
    """Slot pressure with every free row pinned: eviction may reclaim
    refcount-0 cached pages, but a page a live stream attached must
    survive — the reader's stream stays bit-identical — and once readers
    drain, pressure eviction proceeds and the block ledger balances."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate

    rng = np.random.RandomState(5)
    pA = rng.randint(1, 500, size=16).astype(np.int32)    # 2 blocks
    pC = rng.randint(1, 500, size=16).astype(np.int32)
    ref_b = np.asarray(generate(gpt_tiny, pA[None, :],
                                max_new_tokens=8).numpy())[0, 16:]

    eng = _engine(gpt_tiny, serving.SimClock(), num_slots=2, n_blocks=4)
    eng.submit(pA, max_new_tokens=2)        # donor: caches pA's 2 blocks
    _drain_all(eng)
    rb = eng.submit(pA, max_new_tokens=8)   # attaches block 0 (+COW tail)
    eng.pump()                              # admit + prefill: refcount live
    shared_page = rb_attached = None
    with eng._cond:
        (slot_b, req_b), = eng._active.items()
        rb_attached = list(req_b.attached_pages)
    assert len(rb_attached) == 1
    shared_page = rb_attached[0]
    assert eng.pool.refcount[shared_page] == 1
    # pressure: rC needs a row; row0 pinned, row1 is rb's. Eviction may
    # only take refcount-0 pages — the attached page must survive.
    rc = eng.submit(pC, max_new_tokens=2)
    eng.pump()
    assert shared_page in eng.pool.cached           # live reader: kept
    assert eng.pool.refcount.get(shared_page) == 1
    _drain_all(eng)                                 # rb finishes, rc runs
    assert np.array_equal(rb.result(timeout=0), ref_b)
    assert np.array_equal(rc.result(timeout=0)[:2],
                          np.asarray(generate(
                              gpt_tiny, pC[None, :],
                              max_new_tokens=2).numpy())[0, 16:])
    assert eng.prefix_cache.stats["evictions"] >= 1
    eng.pool.check_balance()
    assert eng.pool.active_slots() == 0
    eng.stop()


# ---- multi-tenant scheduling ----

def test_tenant_namespacing_isolates_kv_but_not_correctness(gpt_tiny):
    from paddle_tpu import serving

    rng = np.random.RandomState(9)
    prompt = rng.randint(1, 500, size=16).astype(np.int32)
    eng = _engine(gpt_tiny, serving.SimClock())
    ha = eng.submit(prompt, max_new_tokens=3, tenant="acme")
    _drain_all(eng)
    hb = eng.submit(prompt, max_new_tokens=3, tenant="bravo")
    _drain_all(eng)
    # same prompt, same greedy output — but bravo MISSED the cache:
    # tenants never share KV, so it paid its own full prefill
    assert np.array_equal(ha.result(timeout=0), hb.result(timeout=0))
    assert eng.prefill_tokens == 2 * len(prompt)
    snap = eng.metrics.snapshot()
    assert snap["tenants"]["acme"]["prefix_misses"] == 1
    assert snap["tenants"]["bravo"]["prefix_misses"] == 1
    assert snap["tenants"]["bravo"]["prefix_hit_tokens"] == 0
    assert eng.prefix_cache.cached_blocks("acme") == 2
    assert eng.prefix_cache.cached_blocks("bravo") == 2
    eng.pool.check_balance()
    eng.stop()


def test_tenant_quota_rejects_typed_without_starving_others(gpt_tiny):
    from paddle_tpu import serving

    rng = np.random.RandomState(13)
    prompt = rng.randint(1, 500, size=16).astype(np.int32)   # cost 20
    eng = _engine(gpt_tiny, serving.SimClock(),
                  tenant_max_inflight_tokens=50)
    eng.submit(prompt, max_new_tokens=4, tenant="hog")
    eng.submit(prompt, max_new_tokens=4, tenant="hog")
    with pytest.raises(serving.RejectedError) as exc:
        eng.submit(prompt, max_new_tokens=4, tenant="hog")
    assert exc.value.reason == "tenant_quota"
    assert exc.value.retry_after_s is not None
    # another tenant is unaffected by hog's quota exhaustion
    eng.submit(prompt, max_new_tokens=4, tenant="polite")
    assert eng.metrics.reject_reasons.get("tenant_quota") == 1
    assert eng.metrics.tenants["hog"]["rejected"] == 1
    _drain_all(eng)
    eng.pool.check_balance()
    eng.stop()


def test_tenant_fair_dequeue_within_slo_class(gpt_tiny):
    """Two slots held by tenant A, another A request queued FIRST and a
    B request queued last: when a slot frees while A still occupies the
    other, fair dequeue picks B (zero active usage), not FIFO's next A."""
    from paddle_tpu import serving

    rng = np.random.RandomState(17)
    mk = lambda: rng.randint(1, 500, size=8).astype(np.int32)  # noqa: E731
    eng = _engine(gpt_tiny, serving.SimClock(), num_slots=2,
                  enable_prefix_cache=False)
    a1 = eng.submit(mk(), max_new_tokens=2, tenant="a")
    eng.submit(mk(), max_new_tokens=8, tenant="a")
    eng.pump()                        # a1 + a2 take both slots
    with eng._cond:
        assert sorted(r.tenant for r in eng._active.values()) == ["a", "a"]
    eng.submit(mk(), max_new_tokens=6, tenant="a")    # FIFO-next
    hb = eng.submit(mk(), max_new_tokens=6, tenant="b")
    while not a1.future.done():
        eng.pump()
    eng.pump()                        # a1's slot refills here
    with eng._cond:
        active = sorted(r.tenant for r in eng._active.values())
        queued = [r.tenant for q in eng._queues.values() for r in q]
    assert active == ["a", "b"]       # fairness beat FIFO
    assert queued == ["a"]            # a3 still waits its turn
    _drain_all(eng)
    assert hb.future.done()
    eng.pool.check_balance()
    eng.stop()


# ---- HTTP surface (X-Tenant-Id, per-tenant observability) ----

def _post(url, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_http_tenant_header_and_per_tenant_observability(gpt_tiny):
    from paddle_tpu import serving
    from paddle_tpu.serving.server import _RETRYABLE_REJECTS

    assert "tenant_quota" in _RETRYABLE_REJECTS   # 429 + Retry-After
    eng = serving.LLMEngine(
        gpt_tiny, serving.LLMEngineConfig(num_slots=2, block_len=8,
                                          n_blocks=4, prefill_chunk=16))
    srv = serving.ServingServer(llm_engine=eng, port=0).start()
    base = f"http://{srv.host}:{srv.port}"
    try:
        prompt = list(range(1, 13))
        code, out = _post(f"{base}/generate",
                          {"input_ids": prompt, "max_new_tokens": 2},
                          headers={"X-Tenant-Id": "alpha"})
        assert code == 200 and len(out["tokens"]) == 2
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"{base}/generate",
                  {"input_ids": prompt, "max_new_tokens": 2},
                  headers={"X-Tenant-Id": "bad tenant!"})
        assert exc.value.code == 400
        assert "X-Tenant-Id" in json.loads(exc.value.read())["error"]
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert ('pdtpu_llm_tenant_requests_total{tenant="alpha",'
                'outcome="submitted"} 1') in text
        assert 'pdtpu_llm_tenant_cache_hit_rate{tenant="alpha"}' in text
        assert "pdtpu_llm_prefix_misses_total 1" in text
        assert "pdtpu_llm_cached_blocks" in text
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert "alpha" in health["llm_tenants"]
        t = health["llm_tenants"]["alpha"]
        assert {"cache_hit_rate", "cached_blocks",
                "inflight_tokens"} <= set(t)
    finally:
        srv.stop()
    eng.pool.check_balance()
