"""Detection op + incubate optimizer tests (reference:
operators/detection/*, python/paddle/incubate/optimizer/).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.ops import (box_coder, box_iou, nms, roi_align,
                                   roi_pool, yolo_box)


def test_box_iou():
    a = paddle.to_tensor(np.array([[0, 0, 2, 2]], np.float32))
    b = paddle.to_tensor(np.array([[1, 1, 3, 3], [0, 0, 2, 2],
                                   [4, 4, 5, 5]], np.float32))
    iou = np.asarray(box_iou(a, b).data)
    np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], atol=1e-6)


def test_nms_basic():
    boxes = paddle.to_tensor(np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]], np.float32))
    scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
    kept = np.asarray(nms(boxes, 0.5, scores=scores).data)
    assert kept.tolist() == [0, 2]  # box 1 suppressed by box 0


def test_nms_categories():
    boxes = paddle.to_tensor(np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11]], np.float32))
    scores = paddle.to_tensor(np.array([0.9, 0.8], np.float32))
    cats = paddle.to_tensor(np.array([0, 1], np.int32))
    kept = np.asarray(nms(boxes, 0.5, scores=scores, category_idxs=cats,
                          categories=[0, 1]).data)
    assert sorted(kept.tolist()) == [0, 1]  # different classes: both kept


def test_roi_align_constant_map():
    # constant feature map -> every pooled value equals the constant
    x = paddle.to_tensor(np.full((1, 3, 16, 16), 5.0, np.float32))
    boxes = paddle.to_tensor(np.array([[2, 2, 10, 10]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = roi_align(x, boxes, num, output_size=4)
    arr = np.asarray(out.data)
    assert arr.shape == (1, 3, 4, 4)
    np.testing.assert_allclose(arr, 5.0, atol=1e-5)


def test_roi_align_gradient_flows():
    import jax.numpy as jnp
    x = paddle.to_tensor(np.random.RandomState(0).randn(
        1, 2, 8, 8).astype(np.float32))
    x.stop_gradient = False
    boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = roi_align(x, boxes, num, output_size=2)
    loss = paddle.sum(out)
    loss.backward()
    assert x.grad is not None
    assert float(jnp.abs(x.grad.data).sum()) > 0


def test_roi_pool_shape():
    x = paddle.to_tensor(np.random.RandomState(1).randn(
        2, 3, 16, 16).astype(np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 8, 8], [4, 4, 12, 12],
                                       [0, 0, 15, 15]], np.float32))
    num = paddle.to_tensor(np.array([2, 1], np.int32))
    out = roi_pool(x, boxes, num, output_size=(3, 3))
    assert tuple(out.shape) == (3, 3, 3, 3)


def test_box_coder_roundtrip():
    priors = paddle.to_tensor(np.array(
        [[0, 0, 10, 10], [5, 5, 15, 20]], np.float32))
    targets = paddle.to_tensor(np.array(
        [[1, 1, 9, 9], [4, 6, 16, 18]], np.float32))
    enc = box_coder(priors, None, targets, code_type="encode_center_size")
    assert tuple(enc.shape) == (2, 2, 4)
    # decode the diagonal of the encoding back: should recover targets
    diag = paddle.to_tensor(np.asarray(enc.data)[
        np.arange(2), np.arange(2)])
    dec = box_coder(priors, None, diag, code_type="decode_center_size")
    np.testing.assert_allclose(np.asarray(dec.data),
                               np.asarray(targets.data), atol=1e-4)


def test_yolo_box_shapes():
    N, A, cls, H, W = 1, 2, 3, 4, 4
    x = paddle.to_tensor(np.random.RandomState(2).randn(
        N, A * (5 + cls), H, W).astype(np.float32))
    img = paddle.to_tensor(np.array([[64, 64]], np.int32))
    boxes, scores = yolo_box(x, img, anchors=[10, 13, 16, 30], class_num=cls,
                             conf_thresh=0.01, downsample_ratio=16)
    assert tuple(boxes.shape) == (N, A * H * W, 4)
    assert tuple(scores.shape) == (N, A * H * W, cls)
    b = np.asarray(boxes.data)
    assert (b >= 0).all() and (b <= 63).all()  # clipped to the image


# ---------------- incubate optimizers ----------------

def test_lookahead():
    from paddle_tpu import optimizer as optim
    from paddle_tpu.incubate import LookAhead

    rng = np.random.RandomState(3)
    w0 = rng.randn(4, 4).astype(np.float32)
    lin = paddle.nn.Linear(4, 4, bias_attr=False)
    lin.weight.set_value(w0)
    inner = optim.SGD(learning_rate=0.1, parameters=lin.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))

    fast = w0.copy()
    slow = w0.copy()
    for i in range(4):
        loss = paddle.mean(lin(x) @ lin(x).T)
        loss.backward()
        g = np.asarray(lin.weight.grad.data)
        la.step()
        la.clear_grad()
        fast = fast - 0.1 * g
        if (i + 1) % 2 == 0:
            slow = slow + 0.5 * (fast - slow)
            fast = slow.copy()
        np.testing.assert_allclose(lin.weight.numpy(), fast, atol=1e-5)
    with pytest.raises(ValueError):
        LookAhead(inner, alpha=2.0)


def test_model_average():
    from paddle_tpu import optimizer as optim
    from paddle_tpu.incubate import ModelAverage

    lin = paddle.nn.Linear(2, 2, bias_attr=False)
    w0 = np.zeros((2, 2), np.float32)
    lin.weight.set_value(w0)
    inner = optim.SGD(learning_rate=1.0, parameters=lin.parameters())
    ma = ModelAverage(average_window_rate=1.0, inner_optimizer=inner,
                      min_average_window=100, max_average_window=100)
    x = paddle.to_tensor(np.eye(2, dtype=np.float32))
    seen = []
    for _ in range(3):
        loss = paddle.sum(lin(x))
        loss.backward()
        ma.step()
        ma.clear_grad()
        seen.append(lin.weight.numpy().copy())
    avg = np.mean(seen, axis=0)
    live = lin.weight.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(lin.weight.numpy(), avg, atol=1e-6)
    np.testing.assert_allclose(lin.weight.numpy(), live, atol=1e-6)


# ---- detection op batch (round 3: VERDICT L3 breadth) ----

def test_prior_box_ssd_semantics():
    from paddle_tpu.vision.ops import prior_box
    x = paddle.zeros([1, 8, 2, 2])
    img = paddle.zeros([1, 3, 32, 32])
    boxes, var = prior_box(x, img, min_sizes=[8.0], max_sizes=[16.0],
                           aspect_ratios=[2.0], flip=True, clip=True)
    # priors: ar{1,2,0.5} for min + 1 max box = 4
    assert tuple(boxes.shape) == (2, 2, 4, 4)
    b = np.asarray(boxes.data)
    assert (b >= 0).all() and (b <= 1).all()
    # cell (0,0) center = (0.5*16)/32 = 0.25; ar=1 min box half-width 4/32
    np.testing.assert_allclose(b[0, 0, 0], [0.25 - 0.125, 0.25 - 0.125,
                                            0.25 + 0.125, 0.25 + 0.125],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(var.data)[0, 0, 0],
                               [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator_shapes_and_centers():
    from paddle_tpu.vision.ops import anchor_generator
    x = paddle.zeros([1, 8, 3, 3])
    anchors, var = anchor_generator(x, anchor_sizes=[32.0, 64.0],
                                    aspect_ratios=[1.0],
                                    stride=[16.0, 16.0])
    assert tuple(anchors.shape) == (3, 3, 2, 4)
    a = np.asarray(anchors.data)
    # cell (0,0) center (8, 8); size-32 ar-1 anchor spans +-(32-1)/2
    # (anchor_generator_op.h pixel convention)
    np.testing.assert_allclose(a[0, 0, 0], [-7.5, -7.5, 23.5, 23.5],
                               rtol=1e-5)


def test_box_clip():
    from paddle_tpu.vision.ops import box_clip
    boxes = paddle.to_tensor(np.array(
        [[[-5.0, -5.0, 50.0, 50.0], [10.0, 10.0, 20.0, 20.0]]], np.float32))
    info = paddle.to_tensor(np.array([[40.0, 30.0, 1.0]], np.float32))
    out = np.asarray(box_clip(boxes, info).data)
    np.testing.assert_allclose(out[0, 0], [0, 0, 29, 39])
    np.testing.assert_allclose(out[0, 1], [10, 10, 20, 20])
    # scale=2: the resized 40x30 im_info maps back to a 20x15 original
    info2 = paddle.to_tensor(np.array([[40.0, 30.0, 2.0]], np.float32))
    out2 = np.asarray(box_clip(boxes, info2).data)
    np.testing.assert_allclose(out2[0, 0], [0, 0, 14, 19])


def test_bipartite_match_greedy():
    from paddle_tpu.vision.ops import bipartite_match
    d = paddle.to_tensor(np.array([[0.9, 0.1, 0.3],
                                   [0.2, 0.8, 0.4]], np.float32))
    idx, dist = bipartite_match(d)
    np.testing.assert_array_equal(np.asarray(idx.data), [0, 1, -1])
    np.testing.assert_allclose(np.asarray(dist.data)[:2], [0.9, 0.8])


def test_bipartite_match_per_prediction():
    from paddle_tpu.vision.ops import bipartite_match
    d = paddle.to_tensor(np.array([[0.9, 0.6, 0.3]], np.float32))
    idx, _ = bipartite_match(d, match_type="per_prediction",
                             dist_threshold=0.5)
    # col1 unmatched by greedy (row 0 taken) but 0.6 >= 0.5 -> matched
    np.testing.assert_array_equal(np.asarray(idx.data), [0, 0, -1])


def test_multiclass_nms_basic():
    from paddle_tpu.vision.ops import multiclass_nms
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]  # class 1 (0 is background)
    out, nums = multiclass_nms(paddle.to_tensor(boxes),
                               paddle.to_tensor(scores),
                               score_threshold=0.1, nms_threshold=0.5)
    o = np.asarray(out.data)
    assert np.asarray(nums.data)[0] == 2  # overlapping pair suppressed to 1
    assert o[0][0] == 1.0 and o[0][1] == pytest.approx(0.9)
    np.testing.assert_allclose(o[1][2:], [20, 20, 30, 30])


def test_matrix_nms_decays_overlaps():
    from paddle_tpu.vision.ops import matrix_nms
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out, nums = matrix_nms(paddle.to_tensor(boxes),
                           paddle.to_tensor(scores),
                           score_threshold=0.1, post_threshold=0.0)
    o = np.asarray(out.data)
    # the exact-duplicate's score decays to 0 (iou=1) and drops; the
    # disjoint box survives with its score intact
    assert np.asarray(nums.data)[0] == 2
    assert o[0][1] == pytest.approx(0.9)
    assert o[1][1] == pytest.approx(0.7)
    np.testing.assert_allclose(o[1][2:], [20, 20, 30, 30])


def test_distribute_fpn_proposals():
    from paddle_tpu.vision.ops import distribute_fpn_proposals
    rois = paddle.to_tensor(np.array(
        [[0, 0, 223, 223],      # scale 224 -> refer level 4
         [0, 0, 27, 27],        # scale 28  -> level 2 (clipped)
         [0, 0, 895, 895]],     # scale 896 -> level 6 -> clip to 5
        np.float32))
    outs, restore = distribute_fpn_proposals(rois, 2, 5, 4, 224)
    sizes = [np.asarray(o.data).shape[0] for o in outs]
    assert sizes == [1, 0, 1, 1]
    # restore maps concatenated [lvl2, lvl4, lvl5] back to input order
    cat = np.concatenate([np.asarray(o.data) for o in outs if
                          np.asarray(o.data).size])
    rest = np.asarray(restore.data)
    np.testing.assert_allclose(cat[rest], np.asarray(rois.data))


def test_iou_similarity_alias():
    from paddle_tpu.vision.ops import iou_similarity
    a = paddle.to_tensor(np.array([[0, 0, 10, 10]], np.float32))
    b = paddle.to_tensor(np.array([[0, 0, 10, 10], [5, 5, 15, 15]],
                                  np.float32))
    out = np.asarray(iou_similarity(a, b).data)
    np.testing.assert_allclose(out[0, 0], 1.0, rtol=1e-5)
    assert 0.1 < out[0, 1] < 0.2


def test_multiclass_nms_return_index_and_pixel_coords():
    from paddle_tpu.vision.ops import multiclass_nms
    boxes = np.array([[[0, 0, 10, 10], [30, 30, 40, 40]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]
    out, index, nums = multiclass_nms(
        paddle.to_tensor(boxes), paddle.to_tensor(scores),
        score_threshold=0.1, normalized=False, return_index=True)
    np.testing.assert_array_equal(np.asarray(index.data), [0, 1])
    assert np.asarray(nums.data)[0] == 2


def test_matrix_nms_gaussian_sigma_strength():
    """Reference formula exp(-sigma*(iou^2-comp^2)): LARGER sigma means
    STRONGER suppression."""
    from paddle_tpu.vision.ops import matrix_nms
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]

    def second_score(sigma):
        out, _ = matrix_nms(paddle.to_tensor(boxes),
                            paddle.to_tensor(scores), score_threshold=0.1,
                            use_gaussian=True, gaussian_sigma=sigma)
        return np.asarray(out.data)[1, 1]

    assert second_score(8.0) < second_score(0.5)

