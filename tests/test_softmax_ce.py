"""Fused chunked lm-head+CE parity tests (ops/softmax_ce.py; reference:
softmax_with_cross_entropy + c_softmax_with_cross_entropy_op.cu).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.softmax_ce import fused_linear_cross_entropy


def _dense_ce(h, w, labels, ignore_index=-100):
    logits = (h @ w).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    loss = lse - tl
    return jnp.where(labels == ignore_index, 0.0, loss)


@pytest.mark.parametrize("V,n_chunks", [(1000, 8), (1024, 4), (777, 8),
                                        (50, 8)])
def test_fused_ce_forward_parity(V, n_chunks):
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(32, 64).astype(np.float32))
    w = jnp.asarray(rng.randn(64, V).astype(np.float32) * 0.05)
    y = jnp.asarray(rng.randint(0, V, (32,)).astype(np.int32))
    got = fused_linear_cross_entropy(h, w, y, -100, n_chunks)
    want = _dense_ce(h, w, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                               rtol=1e-4)


def test_fused_ce_grad_parity():
    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    w = jnp.asarray(rng.randn(32, 500).astype(np.float32) * 0.05)
    y = jnp.asarray(rng.randint(0, 500, (16,)).astype(np.int32))

    def f_fused(h, w):
        return jnp.mean(fused_linear_cross_entropy(h, w, y, -100, 8))

    def f_dense(h, w):
        return jnp.mean(_dense_ce(h, w, y))

    gh1, gw1 = jax.grad(f_fused, argnums=(0, 1))(h, w)
    gh2, gw2 = jax.grad(f_dense, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh2), atol=1e-5,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), atol=1e-5,
                               rtol=1e-4)


def test_fused_ce_ignore_index():
    rng = np.random.RandomState(2)
    h = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 100).astype(np.float32) * 0.1)
    y = jnp.asarray(np.array([3, -100, 7, -100, 1, 2, 3, 4], np.int32))
    loss = fused_linear_cross_entropy(h, w, y, -100, 4)
    arr = np.asarray(loss)
    assert arr[1] == 0.0 and arr[3] == 0.0
    assert (arr[[0, 2, 4, 5, 6, 7]] > 0).all()
    # ignored tokens contribute zero gradient
    gh = jax.grad(lambda h: jnp.sum(
        fused_linear_cross_entropy(h, w, y, -100, 4)))(h)
    gh = np.asarray(gh)
    assert np.abs(gh[1]).max() == 0.0 and np.abs(gh[3]).max() == 0.0
    assert np.abs(gh[0]).max() > 0.0


def test_fused_ce_bf16_compute():
    rng = np.random.RandomState(3)
    h = jnp.asarray(rng.randn(16, 32).astype(np.float32)).astype(
        jnp.bfloat16)
    w = (jnp.asarray(rng.randn(32, 300).astype(np.float32)) * 0.05).astype(
        jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 300, (16,)).astype(np.int32))
    got = fused_linear_cross_entropy(h, w, y, -100, 8)
    assert got.dtype == jnp.float32
    want = _dense_ce(h.astype(jnp.float32), w.astype(jnp.float32), y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0.05,
                               rtol=0.05)
    gh, gw = jax.grad(
        lambda h, w: jnp.mean(fused_linear_cross_entropy(h, w, y, -100, 8)),
        argnums=(0, 1))(h, w)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16


def test_gpt_model_loss_matches_dense_path():
    import os
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    rng = np.random.RandomState(4)
    ids = paddle.to_tensor(rng.randint(
        0, model.config.vocab_size, (2, 32)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(
        0, model.config.vocab_size, (2, 32)).astype(np.int32))
    loss_fused = float(model(ids, labels).item())
    os.environ["FLAGS_fused_lm_ce"] = "0"
    try:
        loss_dense = float(model(ids, labels).item())
    finally:
        os.environ.pop("FLAGS_fused_lm_ce")
    np.testing.assert_allclose(loss_fused, loss_dense, rtol=2e-4)
