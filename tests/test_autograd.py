"""Eager autograd tape tests (reference behavior: imperative/basic_engine.cc +
varbase_patch_methods Tensor.backward), including numeric-gradient checks in the
OpTest style."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x_np, eps=1e-3):
    g = np.zeros_like(x_np)
    flat = x_np.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = fn(x_np.copy().reshape(x_np.shape))
        flat[i] = orig - eps
        lo = fn(x_np.copy().reshape(x_np.shape))
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_backward():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 18.0 * x.numpy())


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_no_grad_blocks_tape():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    ((a + b) * 1.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_matmul_grad_numeric():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 4).astype(np.float32)
    b_np = rng.randn(4, 2).astype(np.float32)
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np)
    out = paddle.matmul(a, b).sum()
    out.backward()

    def f(x):
        return float((x @ b_np).sum())

    ng = numeric_grad(f, a_np.copy())
    np.testing.assert_allclose(a.grad.numpy(), ng, rtol=1e-2, atol=1e-2)


def test_paddle_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_detach_stops_gradient():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    z.sum().backward()
    assert x.grad is None


def test_retain_graph_double_backward_pass():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_pylayer_custom_backward():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


# ---------------- double grad (partial_grad_engine.cc create_graph) -------

def test_double_grad_scalar():
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    y = x * x * x
    (g1,) = paddle.grad(y, [x], create_graph=True)
    assert g1._node is not None  # differentiable gradient
    np.testing.assert_allclose(np.asarray(g1.data), [12.0], atol=1e-5)
    (g2,) = paddle.grad(g1, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g2.data), [12.0], atol=1e-5)
    (g3,) = paddle.grad(g2, [x])
    np.testing.assert_allclose(np.asarray(g3.data), [6.0], atol=1e-5)


def test_gradient_penalty_pattern():
    """d/dparams of ||dL/dx||^2 — the WGAN-GP use of double grad."""
    w = paddle.to_tensor(np.array([3.0], np.float32))
    w.stop_gradient = False
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    L = w * x * x
    (gx,) = paddle.grad(L, [x], create_graph=True)
    penalty = paddle.sum(gx * gx)        # (2wx)^2
    (gw,) = paddle.grad(penalty, [w])
    np.testing.assert_allclose(np.asarray(gw.data), [96.0], atol=1e-4)


def test_double_grad_through_layer():
    import paddle_tpu.nn as nn
    paddle.seed(0)
    lin = nn.Linear(3, 1, bias_attr=False)
    x = paddle.to_tensor(np.ones((1, 3), np.float32))
    x.stop_gradient = False
    y = paddle.sum(paddle.tanh(lin(x)))
    (gx,) = paddle.grad(y, [x], create_graph=True)
    gp = paddle.sum(gx * gx)
    (gw,) = paddle.grad(gp, [lin.weight])
    assert gw is not None
    assert float(np.abs(np.asarray(gw.data)).sum()) > 0


def test_double_grad_through_grad_outputs():
    """d(grad)/d(grad_outputs): the cotangent's tape must survive the seed."""
    u = paddle.to_tensor(np.array([3.0], np.float32))
    u.stop_gradient = False
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    y = x * x
    v = u * 1.0
    (g,) = paddle.grad(y, [x], grad_outputs=[v], create_graph=True)
    np.testing.assert_allclose(np.asarray(g.data), [12.0], atol=1e-5)
    (gu,) = paddle.grad(g, [u])
    np.testing.assert_allclose(np.asarray(gu.data), [4.0], atol=1e-5)


def test_double_grad_inplace_raises():
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    y = x * 2.0
    z = y * y
    y[0] = 100.0  # in-place rebind between record and backward
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        paddle.grad(z, [x], create_graph=True)
    # the normal path stays correct
    z2 = (x * 2.0) * (x * 2.0)
    (g,) = paddle.grad(z2, [x])
    np.testing.assert_allclose(np.asarray(g.data), [16.0], atol=1e-5)


def test_failed_create_graph_leaves_clean_state():
    """A raising create_graph backward must not leave stale seeds or
    clobber pre-existing .grad values."""
    import pytest as _pytest
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    # pre-existing grad from an earlier step
    pre = paddle.to_tensor(np.array([1.0], np.float32))
    pre.stop_gradient = False
    (pre * 3.0).backward()
    assert float(pre.grad.data[0]) == 3.0

    y = x * 2.0
    z = y * y
    y[0] = 100.0
    with _pytest.raises(RuntimeError):
        paddle.grad(z, [x, pre], create_graph=True)
    # pre's .grad untouched by the failed call
    assert float(pre.grad.data[0]) == 3.0
    # retry without create_graph: no doubled seed
    (g,) = paddle.grad(z, [x])
    np.testing.assert_allclose(np.asarray(g.data), [16.0], atol=1e-5)
