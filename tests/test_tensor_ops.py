"""Op tests vs numpy reference — the OpTest analog (reference:
python/paddle/fluid/tests/unittests/op_test.py:270: one-op programs checked
against numpy forward + numeric grads)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_numpy():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_arithmetic_ops():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])


def test_matmul():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = paddle.matmul(a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy())


def test_reductions():
    x_np = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    x = paddle.to_tensor(x_np)
    np.testing.assert_allclose(paddle.sum(x).numpy(), x_np.sum(), rtol=1e-6)
    np.testing.assert_allclose(paddle.mean(x, axis=1).numpy(),
                               x_np.mean(1), rtol=1e-6)
    np.testing.assert_allclose(paddle.max(x, axis=0).numpy(), x_np.max(0))
    np.testing.assert_allclose(paddle.min(x).numpy(), x_np.min())


def test_manipulation():
    x = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(x, 1).shape == [2, 12]
    pieces = paddle.split(x, 2, axis=2)
    assert len(pieces) == 2 and pieces[0].shape == [2, 3, 2]
    c = paddle.concat(pieces, axis=2)
    np.testing.assert_allclose(c.numpy(), x.numpy())
    s = paddle.stack([x, x], axis=0)
    assert s.shape == [2, 2, 3, 4]
    assert paddle.squeeze(paddle.unsqueeze(x, 0), 0).shape == [2, 3, 4]


def test_indexing_and_gather():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 2].numpy(), [2, 6, 10])
    idx = paddle.to_tensor(np.array([2, 0]))
    g = paddle.gather(x, idx, axis=0)
    np.testing.assert_allclose(g.numpy(), x.numpy()[[2, 0]])


def test_comparison_and_where():
    a = paddle.to_tensor([1.0, 5.0, 3.0])
    b = paddle.to_tensor([4.0, 2.0, 3.0])
    np.testing.assert_array_equal((a > b).numpy(), [False, True, False])
    w = paddle.where(a > b, a, b)
    np.testing.assert_allclose(w.numpy(), [4, 5, 3])


def test_search_sort_topk():
    x = paddle.to_tensor([[3.0, 1.0, 2.0], [9.0, 7.0, 8.0]])
    vals, idx = paddle.topk(x, k=2)
    np.testing.assert_allclose(vals.numpy(), [[3, 2], [9, 8]])
    np.testing.assert_array_equal(idx.numpy(), [[0, 2], [0, 2]])
    assert paddle.argmax(x, axis=1).numpy().tolist() == [0, 0]
    np.testing.assert_allclose(paddle.sort(x, axis=1).numpy(),
                               np.sort(x.numpy(), 1))


def test_einsum():
    a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    b = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_cast_dtypes():
    x = paddle.to_tensor([1.5, 2.5])
    assert x.astype("int32").numpy().dtype == np.int32
    assert x.astype(paddle.bfloat16).dtype == np.dtype(paddle.bfloat16)


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.rand([4])
    paddle.seed(42)
    b = paddle.rand([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_cumsum_clip_scale():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(paddle.cumsum(x, axis=0).numpy(),
                               np.cumsum(x.numpy(), 0))
    np.testing.assert_allclose(paddle.clip(x, 1.5, 3.5).numpy(),
                               np.clip(x.numpy(), 1.5, 3.5))
    np.testing.assert_allclose(paddle.scale(x, 2.0, 1.0).numpy(),
                               x.numpy() * 2 + 1)


def test_tensor_method_surface_and_inplace():
    """Root fns exposed as Tensor methods + reference in-place ops."""
    import numpy as np
    t = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
    for name in ("nonzero", "rot90", "matrix_power", "erfinv", "frac",
                 "digamma", "lgamma", "histogram", "tensordot",
                 "put_along_axis", "fill_", "zero_", "add_", "subtract_",
                 "clip_"):
        assert hasattr(t, name), name
    # in-place ops are differentiable through the rebind (non-leaf)
    x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
    x.stop_gradient = False
    h = x * 2.0
    h.clip_(min=0.0)
    paddle.sum(h).backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [2.0, 0.0])
    # fill_/zero_ mutate storage (no grad semantics, reference parity)
    y = paddle.to_tensor(np.ones(3, np.float32))
    y.fill_(7.0)
    assert float(np.asarray(y.data).sum()) == 21.0
    y.zero_()
    assert float(np.asarray(y.data).sum()) == 0.0
    np.testing.assert_allclose(
        np.asarray(paddle.rad2deg(paddle.to_tensor(np.pi)).data), 180.0,
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(paddle.deg2rad(paddle.to_tensor(180.0)).data), np.pi,
        rtol=1e-6)
