"""Zero-downtime rolling weight deployment (ISSUE 16): certified
WeightSets, drain→swap→canary→re-admit over a live fleet with zero
dropped streams and zero recompiles, fleet auto-rollback on a failed
canary, and version-skew safety — a stream never stitches two weight
sets, even across crash failover.

Scheduler tests drive the PRODUCTION DeploymentController.pump() and
ReplicaRouter.pump() under a SimClock; one live test exercises the
RouterServer POST /deploy HTTP surface end to end."""
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    from paddle_tpu.utils.fault_injection import set_global_plan
    set_global_plan(None)
    yield
    set_global_plan(None)


def _fleet(gpt_tiny, clock, n=2, plan=None, router_cfg=None, num_slots=4,
           observatory=False):
    from paddle_tpu import serving
    replicas = [
        serving.InProcessReplica(
            serving.LLMEngine(
                gpt_tiny,
                serving.LLMEngineConfig(num_slots=num_slots, block_len=8,
                                        n_blocks=4, max_queue_depth=64,
                                        observatory=observatory),
                clock=clock),
            i, fault_plan=plan)
        for i in range(n)]
    return serving.ReplicaRouter(replicas, router_cfg), replicas


def _drive(router, clock, max_steps=2000, dt=0.01):
    steps = 0
    while router.has_work():
        clock.advance(dt)
        router.pump()
        steps += 1
        assert steps < max_steps, "router failed to converge"
    return steps


def _drive_deploy(router, ctrl, clock, max_steps=6000, dt=0.01):
    """Interleave router + controller pumps until the rollout settles
    AND all traffic has drained — the SimClock analog of live mode."""
    steps = 0
    while ctrl.active() or router.has_work():
        clock.advance(dt)
        router.pump()
        ctrl.pump()
        steps += 1
        assert steps < max_steps, "deploy failed to converge"
    return steps


def _reference(gpt_tiny, prompts, max_new_tokens):
    from paddle_tpu.models.generation import generate
    plen = prompts[0].size
    assert all(p.size == plen for p in prompts)
    out = np.asarray(generate(gpt_tiny, np.stack(prompts),
                              max_new_tokens=max_new_tokens))
    return out[:, plen:]


def _publish(gpt_tiny, directory, version):
    """Publish the model's own params as a certified WeightSet — a
    numerically identical 'new' version, so canaries pass and streams
    stay bit-comparable to the single-engine oracle."""
    from paddle_tpu.checkpoint import WeightSet
    from paddle_tpu.models.generation import make_decoder_fns
    params, _, _ = make_decoder_fns(gpt_tiny)
    return WeightSet.publish(str(directory), version, params)


def _manual_swap(router, name, params, version):
    """Drive one idle replica through the deploy lifecycle by hand —
    fixture setup for version-skew tests, not the controller path."""
    r = router._replica_by_name(name)
    router.drain_replica(name)
    r.swap(params, version)
    assert r.swap_ready()
    router.readmit_replica(name)


# ---- the weight set: publish / certify / refuse ----

def test_weightset_publish_certify_load_roundtrip(gpt_tiny, tmp_path):
    from paddle_tpu.checkpoint import WeightSet

    ws = _publish(gpt_tiny, tmp_path, "v2")
    assert os.path.exists(ws.data_path)
    manifest = ws.certify()
    assert manifest["version"] == "v2"
    assert manifest["format"] == WeightSet.FORMAT
    loaded = WeightSet(str(tmp_path), "v2").load()
    import jax
    from paddle_tpu.models.generation import make_decoder_fns
    params, _, _ = make_decoder_fns(gpt_tiny)
    orig = jax.tree_util.tree_leaves(params)
    back = jax.tree_util.tree_leaves(loaded)
    assert len(orig) == len(back)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_weightset_certify_refuses_typed(gpt_tiny, tmp_path):
    """Every refusal is a typed UncertifiedWeightsError naming WHY:
    missing manifest, bit-rot (CRC), and manifest/version mismatch."""
    from paddle_tpu.checkpoint import UncertifiedWeightsError, WeightSet

    # nothing published at all
    with pytest.raises(UncertifiedWeightsError) as ei:
        WeightSet(str(tmp_path), "v9").certify()
    assert ei.value.reason == "no_manifest"

    ws = _publish(gpt_tiny, tmp_path, "v2")
    # flip one byte mid-file: the manifest CRC must catch it
    with open(ws.data_path, "r+b") as f:
        f.seek(os.path.getsize(ws.data_path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(UncertifiedWeightsError) as ei:
        ws.certify()
    assert ei.value.reason == "crc_mismatch"

    # a manifest claiming a different version than its filename
    ws3 = _publish(gpt_tiny, tmp_path, "v3")
    m = json.load(open(ws3.manifest_path))
    m["version"] = "v4"
    json.dump(m, open(ws3.manifest_path, "w"))
    with pytest.raises(UncertifiedWeightsError) as ei:
        ws3.certify()
    assert ei.value.reason == "version_mismatch"


def test_deploy_refuses_uncertified_weights(gpt_tiny, tmp_path):
    """The controller never lets uncertified bytes near a replica: a
    missing/corrupt manifest is a typed refusal BEFORE any drain."""
    from paddle_tpu import serving
    from paddle_tpu.checkpoint import UncertifiedWeightsError, WeightSet

    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock)
    ctrl = serving.DeploymentController(router)
    with pytest.raises(UncertifiedWeightsError):
        ctrl.start(WeightSet(str(tmp_path), "v2"))
    assert ctrl.status() == {"state": "idle", "history": []}
    assert all(r.deploy_state == "serving" for r in reps)


# ---- replica lifecycle + placement / gauges ----

def test_drain_excludes_from_placement_and_readmit_restores(gpt_tiny):
    """A deploy-draining replica takes no new placements (health word
    'draining') but KEEPS decoding — unlike quarantine — and readmission
    makes it placeable again. weight_version rides /healthz and the
    pdtpu_router_replica_weight_info gauge."""
    from paddle_tpu import serving

    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock)
    router.drain_replica("replica0")
    assert reps[0].health() == "draining"
    hz = router.healthz()
    assert hz["status"] == "degraded"
    assert hz["replicas"]["replica0"] == "draining"
    assert hz["weight_versions"] == {"replica0": "v0", "replica1": "v0"}

    rng = np.random.RandomState(3)
    handles = [router.submit(rng.randint(1, 500, size=(8,)), 4)
               for _ in range(3)]
    assert all(h._replica is reps[1] for h in handles)
    _drive(router, clock)
    assert all(h.result(timeout=0).size == 4 for h in handles)

    router.readmit_replica("replica0")
    assert reps[0].health() == "ok"
    h = router.submit(rng.randint(1, 500, size=(8,)), 4)
    assert h._replica is reps[0]       # lighter again -> placeable
    _drive(router, clock)

    flat = serving.parse_exposition(router.metrics.render())
    assert flat['pdtpu_router_replica_weight_info'
                '{replica="replica0",version="v0"}'] == 1
    assert flat['pdtpu_router_replica_weight_info'
                '{replica="replica1",version="v0"}'] == 1


def test_replace_params_guards(gpt_tiny):
    """The hot swap is refused (typed WeightSwapError) with work in
    flight or a signature-divergent tree; a legal swap advances
    weight_version and flushes the stale-version prefix cache with the
    page ledger balanced."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu import serving

    clock = serving.SimClock()
    eng = serving.LLMEngine(
        gpt_tiny, serving.LLMEngineConfig(num_slots=2, block_len=8,
                                          n_blocks=4, max_queue_depth=8),
        clock=clock)
    params = eng.params
    eng.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=4)
    with pytest.raises(serving.WeightSwapError, match="work in flight"):
        eng.replace_params(params, "v2")
    while eng.has_work():
        clock.advance(0.01)
        eng.pump()
    assert eng.metrics.snapshot()["cached_blocks"] > 0   # finished stream

    # one leaf reshaped: refused, the culprit leaf named
    bad = jax.tree_util.tree_map(lambda x: x, params)
    leaves, treedef = jax.tree_util.tree_flatten(bad)
    i = max(range(len(leaves)), key=lambda j: jnp.ndim(leaves[j]))
    assert jnp.ndim(leaves[i]) > 1
    leaves[i] = jnp.reshape(leaves[i], (-1,))
    with pytest.raises(serving.WeightSwapError, match="signature"):
        eng.replace_params(jax.tree_util.tree_unflatten(treedef, leaves),
                           "v2")
    assert eng.weight_version == "v0"

    assert eng.pool.cached_blocks() > 0
    eng.replace_params(params, "v2")
    assert eng.weight_version == "v2"
    assert eng.pool.cached_blocks() == 0  # old-version KV cannot survive
    assert eng.pool.check_balance()       # ledger stays exact post-flush


def test_swap_stall_gates_canary(gpt_tiny, tmp_path):
    """swap_stall@0:5.0: the canary must NOT run until the stall
    elapses — the controller parks in canary_wait on SimClock time."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock)
    set_global_plan(FaultPlan.from_spec("swap_stall@0:5.0"))
    ws = _publish(gpt_tiny, tmp_path, "v2")
    ctrl = serving.DeploymentController(
        router, serving.DeployConfig(watch_window_s=0.05))
    ctrl.start(ws)
    ctrl.pump()                      # drain replica0 (idle: nothing moves)
    ctrl.pump()                      # settle -> swap (stall armed)
    assert reps[0].deploy_state == "swapping"
    assert reps[0].weight_version == "v2"     # weights ARE in place...
    for _ in range(10):              # ...but the canary gate holds
        clock.advance(0.2)
        ctrl.pump()
    assert ctrl.status()["phase"] == "canary_wait"
    clock.advance(4.0)               # stall over (5.0s total elapsed)
    ctrl.pump()                      # canary_wait -> canary
    _drive_deploy(router, ctrl, clock)
    assert ctrl.status()["state"] == "idle"
    assert ctrl.status()["history"][-1]["outcome"] == "completed"
    from paddle_tpu.utils.fault_injection import global_plan
    assert any("swap_stall" in line for line in global_plan().log)


# ---- the acceptance proof: rolling deploy under load ----

def test_rolling_deploy_zero_drops_no_recompile_bit_identical(
        gpt_tiny, tmp_path, monkeypatch):
    """Roll v0→v2 across a 3-replica fleet MID-decode: every stream
    admitted before the rollout finishes bit-identical to an
    uninterrupted single-engine generate() (zero dropped, zero garbled),
    the whole fleet lands on v2, and the compile observatory sees ZERO
    recompiles — the swap reuses the warm unified-step executable."""
    from paddle_tpu import serving
    from paddle_tpu.obs.compile_observatory import compile_observatory
    from paddle_tpu.obs.flight_recorder import flight_recorder

    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    flight_recorder().clear()
    obs = compile_observatory()
    obs.reset()
    try:
        clock = serving.SimClock()
        router, reps = _fleet(gpt_tiny, clock, n=3, observatory=True)
        rng = np.random.RandomState(7)
        shapes = [rng.randint(1, 500, size=(8,)).astype(np.int32)
                  for _ in range(6)]

        # wave A warms every executable signature the fleet will need
        warm = [router.submit(p, max_new_tokens=10) for p in shapes]
        _drive(router, clock)
        for h in warm:
            assert h.result(timeout=0).size == 10
        obs.mark_warm()

        # wave B: same shapes, swapped mid-flight
        handles = [router.submit(p, max_new_tokens=10) for p in shapes]
        for _ in range(6):
            clock.advance(0.01)
            router.pump()
        assert all(len(h.tokens_so_far()) > 0 for h in handles)

        ws = _publish(gpt_tiny, tmp_path, "v2")
        ctrl = serving.DeploymentController(
            router, serving.DeployConfig(watch_window_s=0.05,
                                         settle_timeout_s=60.0))
        ctrl.start(ws)
        _drive_deploy(router, ctrl, clock)

        ref = _reference(gpt_tiny, shapes, 10)
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(h.result(timeout=0), ref[i])
        assert all(r.weight_version == "v2" for r in reps)
        assert all(r.deploy_state == "serving" for r in reps)
        assert router.healthz()["status"] == "ok"

        # zero recompiles across the whole rollout
        assert obs.recompiles == 0
        events = flight_recorder().snapshot()["events"]
        assert not [e for e in events if e["kind"] == "compile_recompile"]

        # flight story: started -> swap x3 -> complete, in seq order
        started = [e for e in events if e["kind"] == "deploy_started"]
        swaps = [e for e in events if e["kind"] == "deploy_swap"]
        done = [e for e in events if e["kind"] == "deploy_complete"]
        assert len(started) == 1 and len(done) == 1
        assert [e["replica"] for e in swaps] == \
            ["replica0", "replica1", "replica2"]
        assert started[0]["seq"] < swaps[0]["seq"] < done[0]["seq"]
        assert done[0]["replicas"] == ["replica0", "replica1", "replica2"]

        snap = ctrl.metrics.snapshot()
        assert snap["deploys"] == {"started": 1, "completed": 1,
                                   "rolled_back": 0}
        assert snap["swaps"] == 3
        assert snap["canaries"] == {"pass": 3, "fail": 0}
        assert router.metrics.snapshot()["rejected"] == 0   # no drops
        flat = serving.parse_exposition(ctrl.metrics.render())
        assert flat['pdtpu_deploy_deploys_total{outcome="completed"}'] == 1
        assert flat['pdtpu_deploy_version_info{version="v2"}'] == 1
    finally:
        obs.reset()
        obs.disable()


@pytest.mark.fault_matrix
def test_bad_weights_canary_fails_and_fleet_rolls_back(
        gpt_tiny, tmp_path, monkeypatch):
    """deploy_bad_weights@0 NaN-poisons the (certified!) load: the FIRST
    replica's canary must catch the non-finite logits while it is still
    placement-excluded — zero traffic ever lands on the bad weights —
    and the fleet auto-rolls back to v0, with the deploy_canary_fail →
    deploy_rollback sequence in the flight-recorder dump."""
    from paddle_tpu import serving
    from paddle_tpu.obs.flight_recorder import flight_recorder
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    flight_recorder().clear()
    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock)
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 500, size=(8,)).astype(np.int32)
               for _ in range(4)]
    handles = [router.submit(p, max_new_tokens=10) for p in prompts]
    for _ in range(4):
        clock.advance(0.01)
        router.pump()

    set_global_plan(FaultPlan.from_spec("deploy_bad_weights@0"))
    ws = _publish(gpt_tiny, tmp_path, "v2")
    ctrl = serving.DeploymentController(
        router, serving.DeployConfig(watch_window_s=0.05))
    ctrl.start(ws)
    _drive_deploy(router, ctrl, clock)

    # user-visible impact: NONE — every stream bit-identical
    ref = _reference(gpt_tiny, prompts, 10)
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(h.result(timeout=0), ref[i])
    assert all(r.weight_version == "v0" for r in reps)   # rolled back
    assert all(r.deploy_state == "serving" for r in reps)

    rec = ctrl.status()["history"][-1]
    assert rec["outcome"] == "rolled_back"
    assert rec["reason"].startswith("canary_fail:nonfinite_logits")
    snap = ctrl.metrics.snapshot()
    assert snap["deploys"]["rolled_back"] == 1
    assert snap["canaries"]["fail"] == 1

    events = flight_recorder().snapshot()["events"]
    fail = [e for e in events if e["kind"] == "deploy_canary_fail"]
    rb = [e for e in events if e["kind"] == "deploy_rollback"]
    assert len(fail) == 1 and len(rb) == 1
    assert fail[0]["replica"] == "replica0"
    assert fail[0]["reason"].startswith("nonfinite_logits")
    assert fail[0]["seq"] < rb[0]["seq"]
    assert rb[0]["reason"] == rec["reason"]

    # the rollback dumped the black box with the full story
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("pdtpu_flight_")]
    assert dumps, "rollback must dump the flight recorder"
    dumped = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    kinds = [e["kind"] for e in dumped["events"]]
    assert kinds.index("deploy_canary_fail") < kinds.index(
        "deploy_rollback")

    # the restored replica really decodes finitely again
    toks, finite = reps[0].engine.canary_probe([1, 2, 3], 3)
    assert finite and toks.size == 3


# ---- version-skew safety ----

@pytest.mark.fault_matrix
def test_skew_failover_resumes_only_on_same_version_replica(gpt_tiny):
    """A v0-pinned stream that loses its replica mid-decode resumes on
    the v0 survivor — NOT the idle v2 replica that plain load ranking
    would pick — and finishes bit-identical."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import make_decoder_fns
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock, n=3)
    params, _, _ = make_decoder_fns(gpt_tiny)
    _manual_swap(router, "replica2", params, "v2")
    assert [r.weight_version for r in reps] == ["v0", "v0", "v2"]

    rng = np.random.RandomState(13)
    prompts = [rng.randint(1, 500, size=(8,)).astype(np.int32)
               for _ in range(2)]
    handles = [router.submit(p, max_new_tokens=12) for p in prompts]
    assert [h._replica for h in handles] == [reps[0], reps[1]]
    assert all(h.weight_version == "v0" for h in handles)
    for _ in range(5):
        clock.advance(0.01)
        router.pump()
    assert len(handles[0].tokens_so_far()) > 0    # pin is frozen now

    # replica2 (v2) is IDLE — the load ranking would hand it the victim;
    # the version pin must route to busy replica1 (v0) instead
    set_global_plan(FaultPlan.from_spec("replica_crash@0"))
    _drive(router, clock)
    assert handles[0].failovers == 1
    assert handles[0]._replica is reps[1]         # the v0 survivor, not v2
    ref = _reference(gpt_tiny, prompts, 12)
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(h.result(timeout=0), ref[i])
    # replica2 never saw a single one of these streams
    assert reps[2].engine.metrics.snapshot()["completed"] == 0


@pytest.mark.fault_matrix
def test_skew_pending_queue_until_same_version_replica_exists(gpt_tiny):
    """When the last v0 replica dies and only v2 remains, a v0-pinned
    mid-decode stream is PENDING-QUEUED — never resumed on v2 — and
    completes bit-identical the moment a v0 replica comes back."""
    from paddle_tpu import serving
    from paddle_tpu.models.generation import make_decoder_fns
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock)
    params, _, _ = make_decoder_fns(gpt_tiny)
    _manual_swap(router, "replica1", params, "v2")

    rng = np.random.RandomState(17)
    prompt = rng.randint(1, 500, size=(8,)).astype(np.int32)
    h = router.submit(prompt, max_new_tokens=12)
    assert h._replica is reps[0] and h.weight_version == "v0"
    for _ in range(5):
        clock.advance(0.01)
        router.pump()
    emitted = len(h.tokens_so_far())
    assert emitted > 0

    set_global_plan(FaultPlan.from_spec("replica_crash@0"))
    for _ in range(50):                 # plenty of pumps: must NOT place
        clock.advance(0.01)
        router.pump()
    assert h._inner is None and h._replica is None
    assert not h.future.done()
    assert router.has_work()            # zero-drop: kept pending
    assert h.weight_version == "v0"     # the pin survives the wait

    # a v0 replica returns (rollback restored replica1) -> stream resumes
    _manual_swap(router, "replica1", params, "v0")
    _drive(router, clock)
    assert h._replica is reps[1] and h.future.done()
    np.testing.assert_array_equal(
        h.result(timeout=0), _reference(gpt_tiny, [prompt], 12)[0])
    assert h.failovers == 1


@pytest.mark.fault_matrix
def test_replica_crash_mid_rollout_while_another_drains(
        gpt_tiny, tmp_path):
    """The ISSUE 16 fault-matrix scenario: replica1 hard-crashes during
    the rollout while replica0 is deploy-draining. The crash rides the
    normal failover path (v0-pinned victims land on the remaining v0
    replica), the rollout SKIPS the corpse and completes on the
    survivors, and every stream still finishes bit-identical."""
    from paddle_tpu import serving
    from paddle_tpu.obs.flight_recorder import flight_recorder
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    flight_recorder().clear()
    clock = serving.SimClock()
    router, reps = _fleet(gpt_tiny, clock, n=3)
    rng = np.random.RandomState(19)
    prompts = [rng.randint(1, 500, size=(8,)).astype(np.int32)
               for _ in range(6)]
    handles = [router.submit(p, max_new_tokens=10) for p in prompts]
    for _ in range(4):
        clock.advance(0.01)
        router.pump()

    ws = _publish(gpt_tiny, tmp_path, "v2")
    ctrl = serving.DeploymentController(
        router, serving.DeployConfig(watch_window_s=0.05,
                                     settle_timeout_s=60.0))
    ctrl.start(ws)                       # replica0 drains first
    set_global_plan(FaultPlan.from_spec("replica_crash@1"))
    _drive_deploy(router, ctrl, clock)

    ref = _reference(gpt_tiny, prompts, 10)
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(h.result(timeout=0), ref[i])
    rec = ctrl.status()["history"][-1]
    assert rec["outcome"] == "completed"
    assert rec["skipped"] == ["replica1"]
    assert rec["swapped"] == ["replica0", "replica2"]
    assert reps[0].weight_version == "v2"
    assert reps[2].weight_version == "v2"
    assert reps[1].crashed
    events = flight_recorder().snapshot()["events"]
    assert [e["replica"] for e in events
            if e["kind"] == "deploy_skip"] == ["replica1"]
    assert [e for e in events if e["kind"] == "deploy_complete"]


# ---- live HTTP surface ----

def test_router_server_deploy_http(gpt_tiny, tmp_path):
    """POST /deploy rolls the fleet from the HTTP face: 202 + rolling
    status, /debug/deploy converges to idle with a completed record,
    /healthz advertises the new weight versions, and pdtpu_deploy_*
    joins the /metrics scrape. A second POST mid-rollout gets 409."""
    import time as _time
    from paddle_tpu import serving

    ws = _publish(gpt_tiny, tmp_path, "v2")
    router, reps = _fleet(gpt_tiny, serving.MonotonicClock(), n=2)
    server = serving.RouterServer(router).start()
    base = f"http://{server.host}:{server.port}"
    try:
        body = json.dumps({"directory": str(tmp_path),
                           "version": "v2"}).encode()
        req = urllib.request.Request(
            base + "/deploy", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 202
            assert json.loads(r.read())["state"] == "rolling"

        # an overlapping rollout is refused while this one runs
        try:
            with urllib.request.urlopen(req, timeout=60) as r2:
                code = r2.status       # raced past completion: fine
        except urllib.error.HTTPError as e:
            assert e.code == 409

        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            with urllib.request.urlopen(base + "/debug/deploy",
                                        timeout=30) as r:
                st = json.loads(r.read())
            if st["state"] == "idle":
                break
            _time.sleep(0.05)
        assert st["state"] == "idle"
        assert st["history"][-1]["outcome"] == "completed"

        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            hz = json.loads(r.read())
        assert hz["weight_versions"] == {"replica0": "v2",
                                         "replica1": "v2"}
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            flat = serving.parse_exposition(r.read().decode())
        assert flat['pdtpu_deploy_deploys_total{outcome="completed"}'] == 1
        assert flat['pdtpu_router_replica_weight_info'
                    '{replica="replica0",version="v2"}'] == 1
    finally:
        server.stop(drain=False)
