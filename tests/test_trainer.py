"""Dataset-driven trainer run loop (MultiTrainer / train_from_dataset)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static
from paddle_tpu.distributed import MultiTrainer, train_from_dataset


def _model_step():
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda o, y: nn.functional.mse_loss(o, y), opt)
    return step


def _batches(n=12, bs=8):
    rng = np.random.RandomState(0)
    for _ in range(n):
        yield (rng.randn(bs, 8).astype(np.float32),
               rng.randn(bs, 4).astype(np.float32))


def test_multitrainer_runs_epochs_and_counts_steps():
    step = _model_step()
    trainer = MultiTrainer(step, print_period=0)
    first = float(step(*next(_batches(1))).item())
    last = trainer.train_from_dataset(list(_batches(12)), epochs=2)
    assert trainer.steps == 24
    assert float(last.item()) < first


def test_train_from_dataset_with_decoder_and_native_feed(tmp_path):
    # end-to-end through the C++ datafeed: records -> decoder -> train step
    from paddle_tpu.io.native_feed import (RecordFileDataset,
                                           write_record_file)
    rng = np.random.RandomState(0)
    records = []
    for _ in range(10):
        x = rng.randn(8, 8).astype(np.float32)
        y = rng.randn(8, 4).astype(np.float32)
        records.append(x.tobytes() + y.tobytes())
    path = str(tmp_path / "train.rec")
    write_record_file(path, records)

    def decode(raw):
        x = np.frombuffer(raw[:8 * 8 * 4], np.float32).reshape(8, 8)
        y = np.frombuffer(raw[8 * 8 * 4:], np.float32).reshape(8, 4)
        return x, y

    step = _model_step()
    last = train_from_dataset(step, RecordFileDataset([path]),
                              batch_decoder=decode, print_period=0)
    assert np.isfinite(float(last.item()))


def test_static_executor_train_from_dataset():
    step = _model_step()
    exe = static.Executor()
    last = exe.train_from_dataset(program=step, dataset=list(_batches(4)))
    assert np.isfinite(float(last.item()))
    with pytest.raises(TypeError):
        exe.train_from_dataset(program=static.Program(), dataset=[])


# ---------------- fleet datasets (data_set.cc analog) ----------------

def _write_slot_files(tmp_path, n_files=2, per=6):
    import numpy as np
    from paddle_tpu.io.native_feed import write_record_file
    files = []
    v = 0
    for fi in range(n_files):
        recs = []
        for _ in range(per):
            recs.append(f"{v} {v+1} {float(v)}".encode())
            v += 1
        p = str(tmp_path / f"part-{fi}.rec")
        write_record_file(p, recs)
        files.append(p)
    return files


def _parser(line):
    import numpy as np
    a, b, y = line.split()
    return (np.array([float(a), float(b)], np.float32),
            np.array([float(y)], np.float32))


def test_queue_dataset_streams_batches(tmp_path):
    import numpy as np
    from paddle_tpu.distributed import QueueDataset
    ds = QueueDataset()
    ds.init(batch_size=4, thread_num=2, parser=_parser)
    ds.set_filelist(_write_slot_files(tmp_path))
    batches = list(ds)
    assert len(batches) == 3  # 12 samples / 4 (drop_last default)
    x, y = batches[0]
    assert x.shape == (4, 2) and y.shape == (4, 1)
    seen = sorted(float(v) for b in batches for v in b[1].ravel())
    assert len(seen) == 12


def test_in_memory_dataset_shuffles(tmp_path):
    import numpy as np
    from paddle_tpu.distributed import InMemoryDataset
    ds = InMemoryDataset()
    ds.init(batch_size=3, parser=_parser)
    ds.set_filelist(_write_slot_files(tmp_path, n_files=1, per=9))
    with pytest.raises(RuntimeError):
        ds.local_shuffle()
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 9
    before = [float(s[1][0]) for s in ds._memory]
    ds.set_shuffle_seed(5)
    ds.local_shuffle()
    after = [float(s[1][0]) for s in ds._memory]
    assert sorted(before) == sorted(after) and before != after
    batches = list(ds)
    assert len(batches) == 3
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_train_from_dataset_with_queue_dataset(tmp_path):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed import QueueDataset, train_from_dataset

    ds = QueueDataset()
    ds.init(batch_size=4, parser=_parser)
    ds.set_filelist(_write_slot_files(tmp_path))

    paddle.seed(0)
    model = nn.Linear(2, 1)
    opt = optimizer.SGD(learning_rate=0.01,
                        parameters=model.parameters())

    def step(x, y):
        loss = paddle.mean((model(paddle.to_tensor(x))
                            - paddle.to_tensor(y)) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    res = train_from_dataset(step, ds, epochs=2)
    assert res is not None


def test_global_shuffle_partition_is_content_keyed(tmp_path):
    """The cross-rank partition must not depend on load order: shuffling
    memory first must keep the same record subset."""
    from paddle_tpu.distributed import InMemoryDataset

    files = _write_slot_files(tmp_path, n_files=1, per=8)

    def load(order_seed):
        ds = InMemoryDataset()
        ds.init(batch_size=1, parser=_parser)
        ds.set_filelist(files)
        ds.load_into_memory()
        import random
        random.Random(order_seed).shuffle(ds._memory)
        return ds

    ds = load(1)
    keys1 = sorted(ds._record_key(s, 7) % 2 for s in ds._memory)
    ds2 = load(99)
    keys2 = sorted(ds2._record_key(s, 7) % 2 for s in ds2._memory)
    assert keys1 == keys2  # same records -> same partition regardless of order


def test_dataset_drop_last_and_unknown_option(tmp_path):
    from paddle_tpu.distributed import QueueDataset
    files = _write_slot_files(tmp_path, n_files=1, per=5)
    ds = QueueDataset()
    ds.init(batch_size=2, parser=_parser, drop_last=False)
    ds.set_filelist(files)
    assert len(list(ds)) == 3  # 2+2+1
    with pytest.raises(TypeError):
        QueueDataset().init(batch_size=2, bogus_option=1)


def test_in_memory_shuffle_seed_zero_is_deterministic(tmp_path):
    from paddle_tpu.distributed import InMemoryDataset
    files = _write_slot_files(tmp_path, n_files=1, per=8)

    def run():
        ds = InMemoryDataset()
        ds.init(batch_size=1, parser=_parser)
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.set_shuffle_seed(0)
        ds.local_shuffle()
        return [float(s[1][0]) for s in ds._memory]

    assert run() == run()
