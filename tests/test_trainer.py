"""Dataset-driven trainer run loop (MultiTrainer / train_from_dataset)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static
from paddle_tpu.distributed import MultiTrainer, train_from_dataset


def _model_step():
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = paddle.jit.TrainStep(
        model, lambda o, y: nn.functional.mse_loss(o, y), opt)
    return step


def _batches(n=12, bs=8):
    rng = np.random.RandomState(0)
    for _ in range(n):
        yield (rng.randn(bs, 8).astype(np.float32),
               rng.randn(bs, 4).astype(np.float32))


def test_multitrainer_runs_epochs_and_counts_steps():
    step = _model_step()
    trainer = MultiTrainer(step, print_period=0)
    first = float(step(*next(_batches(1))).item())
    last = trainer.train_from_dataset(list(_batches(12)), epochs=2)
    assert trainer.steps == 24
    assert float(last.item()) < first


def test_train_from_dataset_with_decoder_and_native_feed(tmp_path):
    # end-to-end through the C++ datafeed: records -> decoder -> train step
    from paddle_tpu.io.native_feed import (RecordFileDataset,
                                           write_record_file)
    rng = np.random.RandomState(0)
    records = []
    for _ in range(10):
        x = rng.randn(8, 8).astype(np.float32)
        y = rng.randn(8, 4).astype(np.float32)
        records.append(x.tobytes() + y.tobytes())
    path = str(tmp_path / "train.rec")
    write_record_file(path, records)

    def decode(raw):
        x = np.frombuffer(raw[:8 * 8 * 4], np.float32).reshape(8, 8)
        y = np.frombuffer(raw[8 * 8 * 4:], np.float32).reshape(8, 4)
        return x, y

    step = _model_step()
    last = train_from_dataset(step, RecordFileDataset([path]),
                              batch_decoder=decode, print_period=0)
    assert np.isfinite(float(last.item()))


def test_static_executor_train_from_dataset():
    step = _model_step()
    exe = static.Executor()
    last = exe.train_from_dataset(program=step, dataset=list(_batches(4)))
    assert np.isfinite(float(last.item()))
    with pytest.raises(TypeError):
        exe.train_from_dataset(program=static.Program(), dataset=[])
