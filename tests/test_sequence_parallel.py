"""Sequence/context parallelism through the strategy path (parity-plus;
BASELINE long-context requirement): sep_degree shards the token dim over a
`sep` mesh axis, the strategy compiler reports it, and the GSPMD step
matches single-device numerics.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim
from paddle_tpu.models.gpt import GPTForCausalLM
from paddle_tpu.parallel import ShardedTrainStep

from test_parallel import _data, _single_device_losses


@pytest.fixture()
def sep_mesh():
    from paddle_tpu.distributed import DistributedStrategy, fleet
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 4}
    strategy.sequence_parallel = True
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    mesh = hcg.build_mesh()
    yield mesh, strategy
    from paddle_tpu.distributed import topology as topo
    topo._GLOBAL_HCG[0] = None
    topo._GLOBAL_MESH[0] = None


def test_sep_axis_in_mesh(sep_mesh):
    mesh, _ = sep_mesh
    assert "sep" in mesh.axis_names
    assert mesh.shape["sep"] == 4
    assert mesh.shape["data"] == 2


def test_strategy_compiler_reports_sequence_parallel(sep_mesh):
    mesh, strategy = sep_mesh
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    plan = StrategyCompiler().compile(strategy, None, mesh)
    assert plan.sequence_parallel
    assert "sequence_parallel" in plan.applied


def test_sequence_parallel_loss_parity(sep_mesh):
    """dp2 x sep4 training == single-device training on the same batch."""
    mesh, strategy = sep_mesh
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    cfg = model.config
    ids, labels = _data(cfg, B=4, S=64)

    opt1 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    ref_losses = _single_device_losses(model, opt1, ids, labels, steps=3)

    opt2 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, opt2, mesh)
    assert step.sequence_parallel
    assert "sep" in str(step.data_spec)
    sp_losses = [float(step(ids, labels).item()) for _ in range(3)]

    np.testing.assert_allclose(sp_losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_sequence_parallel_input_actually_sharded(sep_mesh):
    mesh, _ = sep_mesh
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, opt, mesh)
    ids, labels = _data(model.config, B=4, S=64)
    _ = step(ids, labels)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, step.data_spec)
    # each device holds a (B/2, S/4) tile of the (4, 64) batch
    assert sh.shard_shape((4, 64)) == (2, 16)


def _compiled_hlo(step, ids, labels):
    import jax.numpy as jnp
    arrays = []
    from jax.sharding import NamedSharding
    for a in (ids, labels):
        arr = jnp.asarray(a)
        arrays.append(jax.device_put(
            arr, NamedSharding(step.mesh, step._spec_for(arr))))
    lowered = step._jitted.lower(
        step._params, step._opt_state, step._buffers, step._extras,
        jnp.float32(1e-3), jnp.int32(1), jax.random.PRNGKey(0),
        tuple(arrays))
    return lowered.compile().as_text()


def test_ring_attention_on_production_path_no_kv_allgather():
    """VERDICT r2 item 3: sep>1 training must NOT all-gather full-sequence
    k/v — the ring island rotates shards via collective-permute instead."""
    from paddle_tpu.distributed import DistributedStrategy, fleet
    from paddle_tpu.distributed import topology as topo
    from paddle_tpu.models.llama import LlamaForCausalLM
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        mesh = fleet.get_hybrid_communicate_group().build_mesh()
        paddle.seed(0)
        model = LlamaForCausalLM.from_preset("llama2-tiny")
        opt = optim.SGD(learning_rate=1e-3, parameters=model.parameters())
        step = ShardedTrainStep(model, opt, mesh, zero_stage=0)
        assert step.sequence_parallel
        ids, labels = _data(model.config, B=2, S=64)
        hlo = _compiled_hlo(step, ids, labels)
        assert "collective-permute" in hlo, "ring ppermute missing from HLO"
        assert "all-gather" not in hlo, (
            "sep-sharded step still all-gathers (the GSPMD-sliced slow "
            "path); ring attention must keep k/v sharded")
    finally:
        topo._GLOBAL_HCG[0] = None
        topo._GLOBAL_MESH[0] = None


def test_ulysses_impl_via_strategy(sep_mesh):
    """sep_impl='ulysses' routes the island to all_to_all attention and
    still matches single-device numerics."""
    mesh, strategy = sep_mesh
    strategy.hybrid_configs.sep_impl = "ulysses"
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    ids, labels = _data(model.config, B=4, S=64)
    opt1 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    ref_losses = _single_device_losses(model, opt1, ids, labels, steps=2)
    opt2 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    plan = StrategyCompiler().compile(strategy, opt2, mesh)
    assert plan.sequence_parallel_impl == "ulysses"
    step = ShardedTrainStep(model, opt2, mesh, plan=plan)
    sp_losses = [float(step(ids, labels).item()) for _ in range(2)]
    np.testing.assert_allclose(sp_losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_sep_impl_gspmd_disables_island(sep_mesh):
    """sep_impl='gspmd' must route to the partitioner-sliced reference (no
    collective-permute ring island) — review finding."""
    mesh, strategy = sep_mesh
    strategy.hybrid_configs.sep_impl = "gspmd"
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    plan = StrategyCompiler().compile(strategy, opt, mesh)
    assert plan.sequence_parallel_impl == "gspmd"
    step = ShardedTrainStep(model, opt, mesh, plan=plan)
    ids, labels = _data(model.config, B=4, S=64)
    hlo = _compiled_hlo(step, ids, labels)
    # GSPMD path gathers k/v; the ring island would show collective-permute
    assert "all-gather" in hlo
