"""Sequence/context parallelism through the strategy path (parity-plus;
BASELINE long-context requirement): sep_degree shards the token dim over a
`sep` mesh axis, the strategy compiler reports it, and the GSPMD step
matches single-device numerics.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim
from paddle_tpu.models.gpt import GPTForCausalLM
from paddle_tpu.parallel import ShardedTrainStep

from test_parallel import _data, _single_device_losses


@pytest.fixture()
def sep_mesh():
    from paddle_tpu.distributed import DistributedStrategy, fleet
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 4}
    strategy.sequence_parallel = True
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    mesh = hcg.build_mesh()
    yield mesh, strategy
    from paddle_tpu.distributed import topology as topo
    topo._GLOBAL_HCG[0] = None
    topo._GLOBAL_MESH[0] = None


def test_sep_axis_in_mesh(sep_mesh):
    mesh, _ = sep_mesh
    assert "sep" in mesh.axis_names
    assert mesh.shape["sep"] == 4
    assert mesh.shape["data"] == 2


def test_strategy_compiler_reports_sequence_parallel(sep_mesh):
    mesh, strategy = sep_mesh
    from paddle_tpu.distributed.fleet.strategy_compiler import \
        StrategyCompiler
    plan = StrategyCompiler().compile(strategy, None, mesh)
    assert plan.sequence_parallel
    assert "sequence_parallel" in plan.applied


def test_sequence_parallel_loss_parity(sep_mesh):
    """dp2 x sep4 training == single-device training on the same batch."""
    mesh, strategy = sep_mesh
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    cfg = model.config
    ids, labels = _data(cfg, B=4, S=64)

    opt1 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    ref_losses = _single_device_losses(model, opt1, ids, labels, steps=3)

    opt2 = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, opt2, mesh)
    assert step.sequence_parallel
    assert "sep" in str(step.data_spec)
    sp_losses = [float(step(ids, labels).item()) for _ in range(3)]

    np.testing.assert_allclose(sp_losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_sequence_parallel_input_actually_sharded(sep_mesh):
    mesh, _ = sep_mesh
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = ShardedTrainStep(model, opt, mesh)
    ids, labels = _data(model.config, B=4, S=64)
    _ = step(ids, labels)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, step.data_spec)
    # each device holds a (B/2, S/4) tile of the (4, 64) batch
    assert sh.shard_shape((4, 64)) == (2, 16)
