"""Pipeline-parallel correctness: shard_map+ppermute schedule must match
single-device training (reference test_pipeline.py/pipeline_mnist.py analog)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim
from paddle_tpu.models.llama import LlamaForCausalLM
from paddle_tpu.parallel.pipeline import PipelinedTrainStep, pipeline_apply


def _pipe_mesh(n):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs.reshape(n), ("pipe",))


def test_pipeline_apply_identity_math():
    """The tick/rotate schedule must reproduce sequential layer application."""
    from jax.sharding import PartitionSpec as P
    n_stages, per_stage = 2, 2
    mesh = _pipe_mesh(n_stages)
    rng = np.random.RandomState(0)
    # 4 "layers", each a matmul with its own weight
    Ws = jnp.asarray(rng.randn(n_stages, per_stage, 8, 8).astype(np.float32)
                     * 0.3)
    x = jnp.asarray(rng.randn(4, 6, 8).astype(np.float32))  # 4 microbatches

    def layer_fn(w, h):
        return jnp.tanh(h @ w)

    def run(stacked, mbs):
        return pipeline_apply(layer_fn, stacked, mbs, n_stages, remat=False)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
        check_vma=False))({"w": Ws}["w"], x)

    # sequential reference
    ref = x
    for s in range(n_stages):
        for i in range(per_stage):
            ref = jnp.tanh(ref @ Ws[s, i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_pipeline_apply_grads_match_sequential():
    from jax.sharding import PartitionSpec as P
    n_stages = 2
    mesh = _pipe_mesh(n_stages)
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(n_stages, 1, 8, 8).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))

    def layer_fn(w, h):
        return jnp.tanh(h @ w)

    def pipe_loss(stacked):
        def run(stacked_, mbs):
            out = pipeline_apply(layer_fn, stacked_, mbs, n_stages,
                                 remat=False)
            return jnp.sum(out ** 2)

        return jax.shard_map(run, mesh=mesh, in_specs=(P("pipe"), P()),
                             out_specs=P(), check_vma=False)(stacked, x)

    def seq_loss(Ws_):
        h = x
        for s in range(n_stages):
            h = jnp.tanh(h @ Ws_[s, 0])
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(pipe_loss)(Ws)
    g_seq = jax.grad(seq_loss)(Ws)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def _ref_losses(model, ids, labels, lr, steps):
    """Unpipelined SGD training on the full batch: the parity target."""
    params, buffers = model.functional_state()

    @jax.jit
    def step_fn(p):
        loss, g = jax.value_and_grad(
            lambda pp: model.functional_call(pp, buffers, ids, labels))(p)
        new_p = jax.tree_util.tree_map(lambda a, gg: a - lr * gg, p, g)
        return loss, new_p

    losses = []
    for _ in range(steps):
        loss, params = step_fn(params)
        losses.append(float(loss))
    return losses


def _parity_case(n_stages, n_layers, n_micro, extra_axes=()):
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset(
        "llama2-tiny", num_hidden_layers=n_layers)
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 8, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    lr = 1e-2
    ref = _ref_losses(model, ids, labels, lr, 3)

    if extra_axes:
        names = tuple(n for n, _ in extra_axes) + ("pipe",)
        sizes = [s for _, s in extra_axes] + [n_stages]
        devs = np.array(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
        mesh = Mesh(devs, names)
    else:
        mesh = _pipe_mesh(n_stages)
    opt = optim.SGD(learning_rate=lr, parameters=model.parameters())
    step = PipelinedTrainStep(model, opt, mesh, n_micro=n_micro)
    losses = [float(step(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-4)
    return step


def test_1f1b_pp2_three_step_parity():
    """pp=2 1F1B losses match unpipelined SGD for 3 steps (verdict item 2)."""
    _parity_case(n_stages=2, n_layers=2, n_micro=2)


def test_1f1b_pp4_three_step_parity():
    """pp=4, 4 layers, n_micro > 2*S ring-buffer wraparound exercised."""
    _parity_case(n_stages=4, n_layers=4, n_micro=8)


def test_1f1b_composes_with_dp():
    """data x pipe mesh: batch sharded over data, grads pmean'd across."""
    _parity_case(n_stages=2, n_layers=2, n_micro=2,
                 extra_axes=(("data", 2),))


def test_1f1b_per_stage_param_memory():
    """Each device holds only its stage's slice of the decoder stack."""
    step = _parity_case(n_stages=4, n_layers=4, n_micro=4)
    total = 0
    per_dev = 0
    for arr in step._stacked.values():
        assert arr.shape[0] == step.n_stages
        shard = arr.addressable_shards[0]
        assert shard.data.shape[0] == 1, "stacked param not stage-sharded"
        total += arr.nbytes
        per_dev += shard.data.nbytes
    assert per_dev * step.n_stages == total
    # decoder params dominate this model: per-device decoder bytes must be a
    # strict fraction of the full stack
    assert per_dev < total / 2


def test_1f1b_global_norm_clip_parity():
    """ClipGradByGlobalNorm under pp=2 must clip by the norm over ALL stages
    (per-rank norms would silently diverge the replicated params)."""
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny")
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 4, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    lr, clip_norm = 1e-2, 0.05  # small clip_norm so clipping activates

    # unpipelined reference with manual global-norm clip
    params, buffers = model.functional_state()

    @jax.jit
    def ref_step(p):
        loss, g = jax.value_and_grad(
            lambda pp: model.functional_call(pp, buffers, ids, labels))(p)
        gsq = sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                  for x in jax.tree_util.tree_leaves(g))
        gn = jnp.sqrt(gsq)
        f = jnp.minimum(clip_norm / jnp.maximum(gn, clip_norm), 1.0)
        new_p = jax.tree_util.tree_map(lambda a, gg: a - lr * f * gg, p, g)
        return loss, new_p

    ref = []
    for _ in range(3):
        loss, params = ref_step(params)
        ref.append(float(loss))

    opt = optim.SGD(learning_rate=lr, parameters=model.parameters(),
                    grad_clip=ClipGradByGlobalNorm(clip_norm))
    step = PipelinedTrainStep(model, opt, mesh=_pipe_mesh(2), n_micro=2)
    losses = [float(step(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-4)


def test_1f1b_composes_with_zero_sharded_optimizer_state():
    """pp x ZeRO: Adam moments sharded over `sharding`, loss parity kept."""
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 8, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def build(zero):
        paddle.seed(0)
        m = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
        opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, ("data", "sharding", "pipe"))
        return PipelinedTrainStep(m, opt, mesh, n_micro=2, zero_stage=zero,
                                  min_shard_numel=0)

    plain = build(0)
    zero = build(1)
    assert zero._use_zero
    # moment slots for large params are physically sharded over `sharding`
    sharded = [
        (k, s) for k, slots in zero._opt_state.items()
        for s, a in slots.items()
        if "sharding" in str(a.sharding.spec)]
    assert sharded, "no optimizer slot carries the sharding axis"
    # per-device slot bytes shrink ~2x for the sharded slots
    for (k, s) in sharded[:3]:
        full = plain._opt_state[k][s]
        shrd = zero._opt_state[k][s]
        full_local = max(sh.data.size for sh in full.addressable_shards)
        shrd_local = max(sh.data.size for sh in shrd.addressable_shards)
        assert shrd_local * 2 == full_local, (k, s)
    # numerics unchanged
    l_plain = [float(plain(ids, labels).item()) for _ in range(3)]
    l_zero = [float(zero(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(l_zero, l_plain, rtol=1e-4, atol=1e-4)


def test_parallelize_routes_zero_into_pipeline():
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.parallel.api import parallelize
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    opt = optim.Adam(learning_rate=1e-2, parameters=model.parameters())
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "sharding", "pipe"))
    s = DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": 1, "min_shard_numel": 0}
    step = parallelize(model, opt, mesh=mesh, strategy=s)
    assert isinstance(step, PipelinedTrainStep)
    assert step._use_zero


def test_pipeline_batch_divisibility_error():
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny")
    opt = optim.SGD(learning_rate=1e-2, parameters=model.parameters())
    step = PipelinedTrainStep(model, opt, mesh=_pipe_mesh(2), n_micro=4)
    ids = jnp.zeros((6, 16), jnp.int32)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        step(ids, ids)


def test_parallelize_rejects_non_lm_models():
    from paddle_tpu.parallel.api import parallelize
    mesh = _pipe_mesh(2)
    model = paddle.vision.models.LeNet(num_classes=10)
    opt = optim.SGD(learning_rate=1e-2, parameters=model.parameters())
    with pytest.raises(ValueError, match="pipeline-stackable"):
        parallelize(model, opt, mesh=mesh)


def test_parallelize_dispatches_pipeline():
    """parallelize() must route pp_degree>1 meshes to the 1F1B step
    (verdict: a pp>1 mesh silently trained replicated in round 1)."""
    from paddle_tpu.distributed import DistributedStrategy, fleet
    from paddle_tpu.distributed.topology import _GLOBAL_HCG, _GLOBAL_MESH
    from paddle_tpu.parallel.api import parallelize

    paddle.seed(0)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        mesh = fleet.get_hybrid_communicate_group().build_mesh()
        model = LlamaForCausalLM.from_preset("llama2-tiny")
        opt = optim.SGD(learning_rate=1e-2,
                        parameters=model.parameters())
        step = parallelize(model, opt, mesh=mesh, strategy=strategy)
        assert isinstance(step, PipelinedTrainStep)
        assert step.n_micro == 2
        cfg = model.config
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
        loss = step(ids, ids)
        assert np.isfinite(float(loss.item()))
    finally:
        _GLOBAL_HCG[0] = None
        _GLOBAL_MESH[0] = None


def test_1f1b_zero_stage2_and_3_parity():
    """pp x ZeRO-2/3 (VERDICT r3 item 2): grads reduce-scattered to the
    owning chunk (stage-2) and params stored chunked with gather-on-use
    (stage-3) must keep exact loss parity with the unsharded pipeline."""
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    cfg = model.config
    rng = np.random.RandomState(1)
    B, S = 8, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def build(zero):
        paddle.seed(0)
        m = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
        opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, ("data", "sharding", "pipe"))
        return PipelinedTrainStep(m, opt, mesh, n_micro=2, zero_stage=zero,
                                  min_shard_numel=0)

    plain = build(0)
    l_plain = [float(plain(ids, labels).item()) for _ in range(3)]

    z2 = build(2)
    assert z2._z2 and not z2._z3
    l_z2 = [float(z2(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(l_z2, l_plain, rtol=1e-4, atol=1e-4)

    z3 = build(3)
    assert z3._z3
    # stage-3: persistent PARAM storage is physically sharded over
    # `sharding` (not just the optimizer slots)
    sharded_params = [k for k, a in z3._stacked.items()
                      if "sharding" in str(a.sharding.spec)]
    assert sharded_params, "no stacked param carries the sharding axis"
    for k in sharded_params[:2]:
        full = plain._stacked[k]
        shrd = z3._stacked[k]
        full_local = max(sh.data.size for sh in full.addressable_shards)
        shrd_local = max(sh.data.size for sh in shrd.addressable_shards)
        assert shrd_local * 2 == full_local, k
    l_z3 = [float(z3(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(l_z3, l_plain, rtol=1e-4, atol=1e-4)


def test_1f1b_zero_stage2_reduce_scatter_in_hlo():
    """Stage-2's grad sync must lower to reduce-scatter for the chunked
    keys — not an all-reduce followed by a slice."""
    paddle.seed(0)
    m = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "sharding", "pipe"))
    step = PipelinedTrainStep(m, opt, mesh, n_micro=2, zero_stage=2,
                              min_shard_numel=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, m.config.vocab_size, (8, 16)), jnp.int32)
    labels = jnp.asarray(
        rng.randint(0, m.config.vocab_size, (8, 16)), jnp.int32)
    txt = step._jitted.lower(
        step._stacked, step._rest, step._opt_state, step._extras,
        jnp.float32(0.01), jnp.int32(1), (ids, labels)).compile().as_text()
    assert "reduce-scatter" in txt, "stage-2 grads did not lower to RS"


def test_parallelize_zero_stage2_no_downgrade_warning():
    import warnings as _w
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.parallel.api import parallelize
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny", num_hidden_layers=2)
    opt = optim.Adam(learning_rate=1e-2, parameters=model.parameters())
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "sharding", "pipe"))
    s = DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": 2, "min_shard_numel": 0}
    with _w.catch_warnings():
        _w.simplefilter("error")
        step = parallelize(model, opt, mesh=mesh, strategy=s)
    assert step._z2


# ---- pp x ep (VERDICT r4 item 3) ----

def _moe_model(**over):
    from paddle_tpu.models.gpt import GPTForCausalLM
    return GPTForCausalLM.from_preset(
        "ernie-moe-tiny", num_hidden_layers=2, moe_every_n_layers=1, **over)


def test_1f1b_composes_with_ep_vs_dp_equivalence():
    """pp2 x (data2 x ep2) must equal pp2 x data4 EXACTLY: same token
    partitioning and per-rank capacity, so the only difference is whether
    experts are physically sharded and exchanged via all_to_all. Any error
    in the explicit-EP dispatch or its AD transpose breaks the allclose."""
    paddle.seed(0)
    model = _moe_model()
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 16, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def build(axes):
        paddle.seed(0)
        m = _moe_model()
        opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
        sizes = [s for _, s in axes]
        devs = np.array(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
        mesh = Mesh(devs, tuple(n for n, _ in axes))
        return PipelinedTrainStep(m, opt, mesh, n_micro=2)

    dp4 = build([("data", 4), ("pipe", 2)])
    l_dp = [float(dp4(ids, labels).item()) for _ in range(3)]

    ep2 = build([("data", 2), ("ep", 2), ("pipe", 2)])
    assert ep2._moe_stack and ep2._ep_n == 2
    # experts are physically sharded over ep
    ep_leaves = [k for k, a in ep2._stacked.items()
                 if "ep" in str(a.sharding.spec)]
    assert ep_leaves, "no stacked param carries the ep axis"
    l_ep = [float(ep2(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(l_ep, l_dp, rtol=2e-4, atol=2e-4)


def test_1f1b_ep_all_to_all_in_hlo():
    """The explicit-EP stage fns must lower to all-to-all collectives."""
    paddle.seed(0)
    m = _moe_model()
    opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("ep", "pipe"))
    step = PipelinedTrainStep(m, opt, mesh, n_micro=2)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, m.config.vocab_size, (4, 16)), jnp.int32)
    labels = jnp.asarray(
        rng.randint(0, m.config.vocab_size, (4, 16)), jnp.int32)
    txt = step._jitted.lower(
        step._stacked, step._rest, step._opt_state, step._extras,
        jnp.float32(0.01), jnp.int32(1), (ids, labels)).compile().as_text()
    assert "all-to-all" in txt, "explicit EP did not lower to all-to-all"


def test_1f1b_moe_matches_eager_when_aux_weight_zero():
    """With generous capacity (no token drops) and aux weight 0, the
    pipelined MoE CE must match eager full-batch training exactly (routing
    is per-token, so microbatching does not change the math)."""
    paddle.seed(0)
    model = _moe_model(moe_aux_loss_weight=0.0, moe_capacity_factor=8.0)
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 8, 16
    ids_n = rng.randint(0, cfg.vocab_size, (B, S))
    labels_n = rng.randint(0, cfg.vocab_size, (B, S))
    lr = 1e-2

    opt = optim.SGD(learning_rate=lr, parameters=model.parameters())
    ref = []
    for _ in range(3):
        loss = model(paddle.to_tensor(ids_n),
                     labels=paddle.to_tensor(labels_n))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ref.append(float(loss.item()))

    paddle.seed(0)
    m2 = _moe_model(moe_aux_loss_weight=0.0, moe_capacity_factor=8.0)
    opt2 = optim.SGD(learning_rate=lr, parameters=m2.parameters())
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("ep", "pipe"))
    step = PipelinedTrainStep(m2, opt2, mesh, n_micro=2)
    losses = [float(step(jnp.asarray(ids_n, jnp.int32),
                         jnp.asarray(labels_n, jnp.int32)).item())
              for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-4)


def test_1f1b_ep_zero2_compose():
    """pp2 x ep2 x sharding2 with ZeRO stage-2: the full deep composition
    (VERDICT r3 items 2+3 together) keeps parity with pp2 x data4 since
    sharding is a batch axis and the token split is identical."""
    paddle.seed(0)
    model = _moe_model()
    cfg = model.config
    rng = np.random.RandomState(3)
    B, S = 16, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def build(axes, zero):
        paddle.seed(0)
        m = _moe_model()
        opt = optim.Adam(learning_rate=1e-2, parameters=m.parameters())
        sizes = [s for _, s in axes]
        devs = np.array(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
        mesh = Mesh(devs, tuple(n for n, _ in axes))
        return PipelinedTrainStep(m, opt, mesh, n_micro=2, zero_stage=zero,
                                  min_shard_numel=0)

    ref = build([("data", 4), ("pipe", 2)], 0)
    l_ref = [float(ref(ids, labels).item()) for _ in range(3)]
    deep = build([("sharding", 2), ("ep", 2), ("pipe", 2)], 2)
    assert deep._z2 and deep._moe_stack
    l_deep = [float(deep(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(l_deep, l_ref, rtol=2e-4, atol=2e-4)


def test_1f1b_moe_aux_weight_matches_microbatched_eager():
    """Nonzero aux weight: the pipeline's per-microbatch aux mean and its
    GRADIENT scaling must match an eager run over the same microbatches
    (catches any aux-cotangent/n_micro mismatch)."""
    paddle.seed(0)
    model = _moe_model(moe_capacity_factor=8.0)
    cfg = model.config
    rng = np.random.RandomState(0)
    B, S = 8, 16
    ids_n = rng.randint(0, cfg.vocab_size, (B, S))
    labels_n = rng.randint(0, cfg.vocab_size, (B, S))
    lr = 1e-2

    opt = optim.SGD(learning_rate=lr, parameters=model.parameters())
    ref = []
    for _ in range(3):
        # eager over the same two microbatches the n_micro=2 pipeline uses
        l1 = model(paddle.to_tensor(ids_n[:4]),
                   labels=paddle.to_tensor(labels_n[:4]))
        l2 = model(paddle.to_tensor(ids_n[4:]),
                   labels=paddle.to_tensor(labels_n[4:]))
        loss = (l1 + l2) * 0.5
        loss.backward()
        opt.step()
        opt.clear_grad()
        ref.append(float(loss.item()))

    paddle.seed(0)
    m2 = _moe_model(moe_capacity_factor=8.0)
    opt2 = optim.SGD(learning_rate=lr, parameters=m2.parameters())
    step = PipelinedTrainStep(m2, opt2, _pipe_mesh(2), n_micro=2)
    losses = [float(step(jnp.asarray(ids_n, jnp.int32),
                         jnp.asarray(labels_n, jnp.int32)).item())
              for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-4)
