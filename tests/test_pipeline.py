"""Pipeline-parallel correctness: shard_map+ppermute schedule must match
single-device training (reference test_pipeline.py/pipeline_mnist.py analog)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim
from paddle_tpu.models.llama import LlamaForCausalLM
from paddle_tpu.parallel.pipeline import PipelinedTrainStep, pipeline_apply


def _pipe_mesh(n):
    devs = np.array(jax.devices()[:n])
    return Mesh(devs.reshape(n), ("pipe",))


def test_pipeline_apply_identity_math():
    """The tick/rotate schedule must reproduce sequential layer application."""
    from jax.sharding import PartitionSpec as P
    n_stages, per_stage = 2, 2
    mesh = _pipe_mesh(n_stages)
    rng = np.random.RandomState(0)
    # 4 "layers", each a matmul with its own weight
    Ws = jnp.asarray(rng.randn(n_stages, per_stage, 8, 8).astype(np.float32)
                     * 0.3)
    x = jnp.asarray(rng.randn(4, 6, 8).astype(np.float32))  # 4 microbatches

    def layer_fn(w, h):
        return jnp.tanh(h @ w)

    def run(stacked, mbs):
        return pipeline_apply(layer_fn, stacked, mbs, n_stages, remat=False)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
        check_vma=False))({"w": Ws}["w"], x)

    # sequential reference
    ref = x
    for s in range(n_stages):
        for i in range(per_stage):
            ref = jnp.tanh(ref @ Ws[s, i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_pipeline_apply_grads_match_sequential():
    from jax.sharding import PartitionSpec as P
    n_stages = 2
    mesh = _pipe_mesh(n_stages)
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(n_stages, 1, 8, 8).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))

    def layer_fn(w, h):
        return jnp.tanh(h @ w)

    def pipe_loss(stacked):
        def run(stacked_, mbs):
            out = pipeline_apply(layer_fn, stacked_, mbs, n_stages,
                                 remat=False)
            return jnp.sum(out ** 2)

        return jax.shard_map(run, mesh=mesh, in_specs=(P("pipe"), P()),
                             out_specs=P(), check_vma=False)(stacked, x)

    def seq_loss(Ws_):
        h = x
        for s in range(n_stages):
            h = jnp.tanh(h @ Ws_[s, 0])
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(pipe_loss)(Ws)
    g_seq = jax.grad(seq_loss)(Ws)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def test_pipelined_train_step_matches_single_device():
    paddle.seed(0)
    model = LlamaForCausalLM.from_preset("llama2-tiny")
    cfg = model.config
    mesh = _pipe_mesh(2)
    rng = np.random.RandomState(0)
    B, S = 4, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    # single-device reference loss (same params)
    params, buffers = model.functional_state()

    def ref_loss(p):
        out = model.functional_call(p, buffers, ids, labels)
        return out

    ref = float(jax.jit(ref_loss)(params))

    opt = optim.SGD(learning_rate=1e-2, parameters=model.parameters())
    step = PipelinedTrainStep(model, opt, mesh, n_micro=2)
    losses = [float(step(ids, labels).item()) for _ in range(3)]
    np.testing.assert_allclose(losses[0], ref, rtol=1e-4)
    assert losses[2] < losses[0], "pipeline training is not reducing loss"
