"""Interleaved 1F1B (virtual pipeline stages) — parity-plus: the reference
ships only plain 1F1B (section_worker.cc:149); the interleaved schedule is
the Megatron-style bubble reduction, here as a host-simulated lockstep tick
table (pipeline._interleaved_schedule) executed by run_interleaved_1f1b.

Every test asserts exact loss parity against the plain-1F1B pipeline on the
same seed/data: the schedule must not change the math."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as optim
from paddle_tpu.models.llama import LlamaForCausalLM
from paddle_tpu.parallel.pipeline import (PipelinedTrainStep,
                                          _interleaved_schedule)

pytestmark = pytest.mark.slow


def _mesh(axes):
    import jax
    from jax.sharding import Mesh
    sizes = [s for _, s in axes]
    devs = np.array(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
    return Mesh(devs, tuple(n for n, _ in axes))


def _build(V, axes, n_micro=2, layers=8, lr=1e-4):
    paddle.seed(0)
    m = LlamaForCausalLM.from_preset("llama2-tiny",
                                     num_hidden_layers=layers)
    o = optim.AdamW(learning_rate=lr, parameters=m.parameters())
    return m, PipelinedTrainStep(m, o, _mesh(axes), n_micro=n_micro,
                                 virtual_pp_degree=V)


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    return (np.asarray(rng.randint(0, 512, (8, 64)), np.int32),
            np.asarray(rng.randint(0, 512, (8, 64)), np.int32))


@pytest.fixture(scope="module")
def ref_losses(data):
    ids, labels = data
    _, step = _build(1, [("data", 4), ("pipe", 2)])
    return [float(step(ids, labels).item()) for _ in range(2)]


class TestSchedule:
    def test_megatron_length(self):
        # T = V*M + 2(S-1) + (V-1)*S — the Megatron interleaved length
        for S, V, M in [(2, 2, 4), (4, 2, 8), (4, 4, 8)]:
            T, f, b, n_buf = _interleaved_schedule(S, V, M)
            assert T == V * M + 2 * (S - 1) + (V - 1) * S, (S, V, M, T)
            assert f.shape == (T, S, 3) and b.shape == (T, S, 3)
            # every unit executes exactly once
            assert f[:, :, 2].sum() == V * M * S
            assert b[:, :, 2].sum() == V * M * S

    def test_beats_plain_for_deep_pipes(self):
        # chunk-tick count strictly below V * plain-1F1B ticks when S > 2
        S, V, M = 4, 2, 8
        T, _, _, _ = _interleaved_schedule(S, V, M)
        assert T < V * (M + 2 * (S - 1))

    def test_rejects_bad_micro(self):
        with pytest.raises(ValueError):
            _interleaved_schedule(4, 2, 6)  # M % S != 0


class TestParity:
    def test_v2_matches_v1_two_steps(self, data, ref_losses):
        ids, labels = data
        _, s2 = _build(2, [("data", 4), ("pipe", 2)])
        for ref in ref_losses:
            got = float(s2(ids, labels).item())
            np.testing.assert_allclose(got, ref, rtol=5e-5, atol=5e-5)

    def test_v4_matches(self, data, ref_losses):
        ids, labels = data
        _, s4 = _build(4, [("data", 4), ("pipe", 2)])
        np.testing.assert_allclose(float(s4(ids, labels).item()),
                                   ref_losses[0], rtol=2e-5, atol=2e-5)

    def test_deep_pipe_matches(self, data, ref_losses):
        ids, labels = data
        _, s = _build(2, [("data", 2), ("pipe", 4)], n_micro=4)
        np.testing.assert_allclose(float(s(ids, labels).item()),
                                   ref_losses[0], rtol=2e-5, atol=2e-5)

    def test_tp_composition(self, data, ref_losses):
        ids, labels = data
        _, s = _build(2, [("data", 2), ("model", 2), ("pipe", 2)])
        np.testing.assert_allclose(float(s(ids, labels).item()),
                                   ref_losses[0], rtol=2e-5, atol=2e-5)


class TestIntegration:
    def test_sync_to_model_interleaved_unstack(self, data):
        ids, labels = data
        m, s = _build(2, [("data", 4), ("pipe", 2)], lr=1e-2)
        before = {k: np.asarray(v.data).copy()
                  for k, v in dict(m.named_parameters()).items()}
        s(ids, labels)
        s.sync_to_model()
        after = {k: np.asarray(v.data)
                 for k, v in dict(m.named_parameters()).items()}
        changed = sum(not np.allclose(before[k], after[k])
                      for k in before)
        assert changed > len(before) * 0.8
        out = m(paddle.to_tensor(ids), labels=paddle.to_tensor(labels))
        v = out[0] if isinstance(out, tuple) else out
        assert np.isfinite(float(v.item()))

    def test_parallelize_wires_vpp(self, data):
        ids, labels = data
        from paddle_tpu.distributed import DistributedStrategy, fleet
        from paddle_tpu.parallel import parallelize
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1}
        strategy.pipeline_configs = {"accumulate_steps": 2,
                                     "virtual_pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        mesh = fleet.get_hybrid_communicate_group().build_mesh()
        paddle.seed(0)
        m = LlamaForCausalLM.from_preset("llama2-tiny",
                                         num_hidden_layers=8)
        o = optim.AdamW(learning_rate=1e-4, parameters=m.parameters())
        step = parallelize(m, o, mesh, strategy=strategy)
        assert step.n_chunks == 2
        assert np.isfinite(float(step(ids, labels).item()))

    def test_vpp_zero2_and_3_parity(self, data):
        """vpp x ZeRO-2/3 (VERDICT r4 item 6): grad reduce-scatter and
        chunked param storage over the interleaved [pipe, chunk, scan]
        layout must keep exact loss parity with unsharded vpp."""
        ids, labels = data
        axes = [("data", 2), ("sharding", 2), ("pipe", 2)]

        def build(zero):
            paddle.seed(0)
            m = LlamaForCausalLM.from_preset("llama2-tiny",
                                             num_hidden_layers=8)
            o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
            return PipelinedTrainStep(m, o, _mesh(axes), n_micro=2,
                                      zero_stage=zero, virtual_pp_degree=2,
                                      min_shard_numel=0)

        plain = build(0)
        ref = [float(plain(ids, labels).item()) for _ in range(2)]
        z2 = build(2)
        assert z2._z2 and not z2._z3
        got2 = [float(z2(ids, labels).item()) for _ in range(2)]
        np.testing.assert_allclose(got2, ref, rtol=1e-4, atol=1e-4)
        z3 = build(3)
        assert z3._z3
        # interleaved param storage is physically sharding-chunked
        assert any("sharding" in str(a.sharding.spec)
                   for a in z3._stacked.values())
        got3 = [float(z3(ids, labels).item()) for _ in range(2)]
        np.testing.assert_allclose(got3, ref, rtol=1e-4, atol=1e-4)

    def test_lamb_under_vpp_matches_plain_pp(self, data):
        """Lamb trust ratios must be per-LAYER-row in the interleaved
        [pipe, chunk, scan] layout (norm batch dims 3): vpp=2 Lamb training
        matches plain-1F1B Lamb training exactly (VERDICT r4 item 6)."""
        ids, labels = data

        def build(V):
            paddle.seed(0)
            m = LlamaForCausalLM.from_preset("llama2-tiny",
                                             num_hidden_layers=8)
            o = optim.Lamb(learning_rate=1e-3, parameters=m.parameters())
            return PipelinedTrainStep(m, o, _mesh([("data", 4),
                                                   ("pipe", 2)]),
                                      n_micro=2, virtual_pp_degree=V)

        ref_step = build(1)
        ref = [float(ref_step(ids, labels).item()) for _ in range(3)]
        vpp_step = build(2)
        got = [float(vpp_step(ids, labels).item()) for _ in range(3)]
        np.testing.assert_allclose(got, ref, rtol=5e-5, atol=5e-5)

    def test_lars_under_vpp_matches_plain_pp(self, data):
        ids, labels = data

        def build(V):
            paddle.seed(0)
            m = LlamaForCausalLM.from_preset("llama2-tiny",
                                             num_hidden_layers=8)
            o = optim.LarsMomentum(learning_rate=1e-3, momentum=0.9,
                                   parameters=m.parameters())
            return PipelinedTrainStep(m, o, _mesh([("data", 4),
                                                   ("pipe", 2)]),
                                      n_micro=2, virtual_pp_degree=V)

        ref_step = build(1)
        ref = [float(ref_step(ids, labels).item()) for _ in range(3)]
        vpp_step = build(2)
        got = [float(vpp_step(ids, labels).item()) for _ in range(3)]
        np.testing.assert_allclose(got, ref, rtol=5e-5, atol=5e-5)
