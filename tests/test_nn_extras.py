"""New nn surface: HSigmoidLoss, LayerDict, PairwiseDistance, in-place
activations, sequence_mask/diag_embed/affine_grid/grid_sample/gather_tree,
detection-free loss fns (reference: nn/layer/loss.py,
nn/functional/{loss,common,activation}.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def test_layer_dict():
    d = nn.LayerDict({"a": nn.Linear(2, 3), "b": nn.ReLU()})
    assert len(d) == 2 and "a" in d and list(d.keys()) == ["a", "b"]
    d["c"] = nn.Linear(3, 1)
    assert isinstance(d["c"], nn.Linear)
    assert len(list(d.parameters())) == 4  # two Linears x (w, b)
    d.pop("c")
    assert len(d) == 2
    d.clear()
    assert len(d) == 0


def test_pairwise_distance():
    pd = nn.PairwiseDistance(p=2.0)
    x = paddle.to_tensor(np.array([[0.0, 0.0], [1.0, 1.0]], np.float32))
    y = paddle.to_tensor(np.array([[3.0, 4.0], [1.0, 1.0]], np.float32))
    out = np.asarray(pd(x, y).data)
    np.testing.assert_allclose(out, [5.0, 0.0], atol=1e-4)


def test_hsigmoid_loss():
    paddle.seed(0)
    layer = nn.HSigmoidLoss(feature_size=8, num_classes=6)
    x = paddle.randn([4, 8])
    y = paddle.to_tensor(np.array([0, 2, 5, 3], np.int64))
    loss = layer(x, y)
    arr = np.asarray(loss.data)
    assert arr.shape == (4, 1) and (arr > 0).all()
    # trains: loss decreases under SGD
    from paddle_tpu import optimizer as optim
    opt = optim.SGD(learning_rate=0.5, parameters=layer.parameters())
    first = float(paddle.mean(layer(x, y)).item())
    for _ in range(20):
        l = paddle.mean(layer(x, y))
        l.backward()
        opt.step()
        opt.clear_grad()
    assert float(paddle.mean(layer(x, y)).item()) < first


def test_inplace_activations():
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    r = F.relu_(x)
    assert r is x
    np.testing.assert_allclose(np.asarray(x.data), [0.0, 2.0])
    t = paddle.to_tensor(np.zeros(3, np.float32))
    assert F.tanh_(t) is t and F.softmax_(t) is t and \
        F.elu_(paddle.to_tensor(np.ones(2, np.float32))) is not None


def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], np.int64)),
                        maxlen=4)
    np.testing.assert_array_equal(np.asarray(m.data),
                                  [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_diag_embed():
    out = F.diag_embed(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(np.asarray(out.data), [[1, 0], [0, 2]])
    off = F.diag_embed(paddle.to_tensor(np.array([1.0], np.float32)),
                       offset=1)
    assert off.shape[-1] == 2 and np.asarray(off.data)[0, 1] == 1.0


def test_affine_grid_identity_and_grid_sample():
    # identity theta reproduces the image under bilinear sampling
    theta = paddle.to_tensor(np.array(
        [[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]], np.float32))
    grid = F.affine_grid(theta, (1, 1, 4, 4), align_corners=True)
    assert tuple(grid.shape) == (1, 4, 4, 2)
    img = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(
        1, 1, 4, 4))
    out = F.grid_sample(img, grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.asarray(img.data), atol=1e-5)


def test_grid_sample_nearest_border():
    img = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(
        1, 1, 2, 2))
    # sample far out of bounds with border padding: clamps to corner
    g = paddle.to_tensor(np.array([[[[5.0, 5.0]]]], np.float32))
    out = F.grid_sample(img, g, mode="nearest", padding_mode="border")
    assert float(np.asarray(out.data).ravel()[0]) == 3.0


def test_gather_tree():
    # T=3, B=1, beam=2 (reference gather_tree example semantics)
    ids = paddle.to_tensor(np.array(
        [[[2, 2]], [[6, 1]], [[3, 9]]], np.int64))
    parents = paddle.to_tensor(np.array(
        [[[0, 0]], [[1, 1]], [[0, 0]]], np.int64))
    out = np.asarray(F.gather_tree(ids, parents).data)
    assert out.shape == (3, 1, 2)
    # beam 0 back-trace: step2 id 3 (parent 0) <- step1 id 6 (parent 1)
    # <- step0 id ids[0][1]=2  =>  forward sequence [2, 6, 3]
    np.testing.assert_array_equal(out[:, 0, 0], [2, 6, 3])
    # beam 1: 9 (parent 0) <- 6 (parent 1) <- 2  =>  [2, 6, 9]
    np.testing.assert_array_equal(out[:, 0, 1], [2, 6, 9])


def test_loss_fns():
    p = paddle.to_tensor(np.array([0.9, 0.1], np.float32))
    y = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    ll = np.asarray(F.log_loss(p, y).data)
    np.testing.assert_allclose(
        ll, [-np.log(0.9 + 1e-4), -np.log(0.9 + 1e-4)], atol=1e-4)

    se = F.square_error_cost(paddle.to_tensor([2.0]),
                             paddle.to_tensor([5.0]))
    assert float(se.item()) == 9.0

    # dice loss of a perfect one-hot prediction ~ 0
    pred = paddle.to_tensor(np.array([[[0.0, 1.0], [1.0, 0.0]]], np.float32))
    lab = paddle.to_tensor(np.array([[[1], [0]]], np.int64))
    dl = float(F.dice_loss(pred, lab).item())
    assert dl < 0.01

    logit = paddle.to_tensor(np.array([[2.0, -2.0]], np.float32))
    lab2 = paddle.to_tensor(np.array([[1.0, 0.0]], np.float32))
    fl = float(F.sigmoid_focal_loss(logit, lab2, reduction="sum").item())
    assert 0 < fl < 0.1  # confident correct predictions: tiny focal loss

    a = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(
        np.float32))
    pos = paddle.to_tensor(np.random.RandomState(1).randn(4, 8).astype(
        np.float32))
    labs = paddle.to_tensor(np.array([0, 0, 1, 1], np.int64))
    nl = float(F.npair_loss(a, pos, labs).item())
    assert np.isfinite(nl)


def test_inplace_relu_gradient_flows():
    """relu_ must contribute its derivative to the tape (not a silent
    data swap)."""
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    x.stop_gradient = False
    h = x * 2.0
    F.relu_(h)
    paddle.sum(h).backward()
    np.testing.assert_allclose(np.asarray(x.grad.data), [0.0, 2.0])
    # leaf-requiring-grad guard
    leaf = paddle.to_tensor(np.ones(2, np.float32))
    leaf.stop_gradient = False
    with pytest.raises(RuntimeError):
        F.relu_(leaf)


def test_spectral_norm_sigma_gradient():
    """d(W/sigma)/dW must include the -W uv^T/sigma^2 term: for a 1x1
    weight the normalized value is sign(w), whose gradient is ~0."""
    from paddle_tpu.nn.utils import spectral_norm
    lin = nn.Linear(1, 1, bias_attr=False)
    lin.weight.set_value(np.array([[2.0]], np.float32))
    spectral_norm(lin, n_power_iterations=8)
    x = paddle.to_tensor(np.ones((1, 1), np.float32))
    out = lin(x)
    out.backward()
    g = float(np.asarray(lin.weight_orig.grad.data).ravel()[0])
    assert abs(g) < 1e-4, g


def test_remove_weight_norm_dim1_size1():
    from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
    lin = nn.Linear(3, 1, bias_attr=False)  # weight [3, 1]
    x = paddle.randn([2, 3])
    y0 = np.asarray(lin(x).data)
    weight_norm(lin, dim=1)
    remove_weight_norm(lin)
    np.testing.assert_allclose(np.asarray(lin(x).data), y0, atol=1e-5)


def test_diag_embed_dim_order():
    x = paddle.to_tensor(np.array([1.0], np.float32))
    sup = np.asarray(F.diag_embed(x, offset=1, dim1=-2, dim2=-1).data)
    sub = np.asarray(F.diag_embed(x, offset=1, dim1=-1, dim2=-2).data)
    np.testing.assert_allclose(sub, sup.T)
    assert sup[0, 1] == 1.0 and sub[1, 0] == 1.0


def test_grid_sample_reflection():
    img = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(
        1, 1, 2, 2))
    # x just beyond the right edge reflects back inside
    g = paddle.to_tensor(np.array([[[[1.5, -1.0]]]], np.float32))
    out = F.grid_sample(img, g, padding_mode="reflection",
                        align_corners=True)
    assert 0.0 <= float(np.asarray(out.data).ravel()[0]) <= 3.0
    with pytest.raises(ValueError):
        F.grid_sample(img, g, padding_mode="bogus")


def test_inplace_grad_wrt_intermediate():
    """paddle.grad w.r.t. the rebound in-place tensor must see the
    POST-activation cotangent (node.outputs rebind)."""
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    x.stop_gradient = False
    h = x * 2.0
    F.relu_(h)
    (g,) = paddle.grad(paddle.sum(h), [h])
    np.testing.assert_allclose(np.asarray(g.data), [1.0, 1.0])


def test_layer_dict_from_layer_dict():
    d1 = nn.LayerDict({"fc": nn.Linear(2, 3)})
    d2 = nn.LayerDict(d1)
    assert "fc" in d2 and isinstance(d2["fc"], nn.Linear)


def test_grid_sample_bad_mode_raises():
    img = paddle.to_tensor(np.zeros((1, 1, 2, 2), np.float32))
    g = paddle.to_tensor(np.zeros((1, 1, 1, 2), np.float32))
    with pytest.raises(ValueError):
        F.grid_sample(img, g, mode="nearst")
