"""Sequence-parallel attention correctness vs full attention."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops.attention import _attention_reference
from paddle_tpu.parallel.ring_attention import (ring_attention,
                                                ulysses_attention)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("sep",))


def _qkv(B=2, H=4, S=64, D=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv()
    scale = 1.0 / np.sqrt(q.shape[-1])
    ref = _attention_reference(q, k, v, causal, scale)

    def f(ql, kl, vl):
        return ring_attention(ql, kl, vl, causal=causal)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "sep"), P(None, None, "sep"),
                  P(None, None, "sep")),
        out_specs=P(None, None, "sep"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    n = 4
    mesh = _mesh(n)
    q, k, v = _qkv(H=4)
    scale = 1.0 / np.sqrt(q.shape[-1])
    ref = _attention_reference(q, k, v, causal, scale)

    def f(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, causal=causal)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "sep"),) * 3,
        out_specs=P(None, None, "sep"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_ring_attention_grads_match_full():
    n = 2
    mesh = _mesh(n)
    q, k, v = _qkv(B=1, H=2, S=32, D=8)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def ring_loss(q_, k_, v_):
        def f(ql, kl, vl):
            return ring_attention(ql, kl, vl, causal=True)

        out = jax.shard_map(
            f, mesh=mesh, in_specs=(P(None, None, "sep"),) * 3,
            out_specs=P(None, None, "sep"), check_vma=False)(q_, k_, v_)
        return jnp.sum(out ** 2)

    def ref_loss(q_, k_, v_):
        return jnp.sum(_attention_reference(q_, k_, v_, True, scale) ** 2)

    gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3,
                                   atol=1e-4)
