"""End-to-end observability (ISSUE 9): per-request tracing (traceparent
ingestion, phase spans that tile the recorded latency, the bounded
timeline LRU behind /debug/requests), the process-global flight recorder
(ring bound, atomic dumps, postmortem CLI), the shared Prometheus
plumbing (`pdtpu_train_*` exporter + opt-in MetricsServer), and the
fault-matrix scenario proving a breaker-open cascade leaves a black-box
dump that names the quarantined request.

Engine integration tests run the PRODUCTION schedulers threadless under
a SimClock, so every timeline number is exact, not approximate."""
import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import obs, profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "flight_recorder.py")


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


# ---- request-trace primitives ----

def test_ingest_traceparent_and_request_ids():
    tid = "0af7651916cd43dd8448eb211c80319c"
    hdr = f"00-{tid}-b7ad6b7169203331-01"
    assert obs.ingest_traceparent(hdr) == tid
    assert obs.ingest_traceparent(hdr.upper()) == tid       # case-folded
    assert obs.ingest_traceparent("  " + hdr + "  ") == tid
    assert obs.ingest_traceparent(None) is None
    assert obs.ingest_traceparent("") is None
    assert obs.ingest_traceparent("not-a-traceparent") is None
    assert obs.ingest_traceparent("00-xyz-b7ad6b7169203331-01") is None
    rid = obs.new_request_id()
    assert len(rid) == 32 and rid != obs.new_request_id()


def test_request_trace_phases_tile_latency():
    tr = obs.RequestTrace("ab" * 16, 10.0, slo="interactive", tenant="t0")
    tr.mark("admitted", 10.004)
    tr.mark("admitted", 99.0)           # marks record at most once
    tr.mark("first_token", 10.010)
    tr.event("decode_step", 10.011, tok=7)
    tr.finish(10.020, "completed")
    tr.finish(10.5, "failed")           # finish is idempotent too
    d = tr.to_dict()
    assert d["outcome"] == "completed"
    assert d["slo"] == "interactive" and d["tenant"] == "t0"
    assert [p["name"] for p in d["phases"]] == ["queued", "prefill",
                                                "decode"]
    # the tiling contract: phase durations sum EXACTLY to the latency
    assert sum(p["dur_ms"] for p in d["phases"]) == \
        pytest.approx(d["latency_ms"])
    assert d["latency_ms"] == pytest.approx(20.0)
    assert d["ttft_ms"] == pytest.approx(10.0)
    assert d["marks_ms"]["admitted"] == pytest.approx(4.0)
    assert d["events"][0]["name"] == "decode_step"
    assert d["events"][0]["args"] == {"tok": 7}
    # chrome view: one X span per phase + an instant per event, one lane
    ev = tr.chrome_events()
    xs = [e for e in ev if e["ph"] == "X"]
    assert len(xs) == 3
    assert all(e["name"].startswith("req/abababab/") for e in ev)
    assert len({e["tid"] for e in ev}) == 1


def test_request_trace_unfinished_and_event_bound():
    tr = obs.RequestTrace("cd" * 16, 0.0)
    assert tr.phases() == []            # no finish mark yet -> no spans
    assert tr.to_dict()["latency_ms"] is None
    for i in range(obs.RequestTrace.MAX_EVENTS + 5):
        tr.event("e", float(i))
    assert len(tr.events) == obs.RequestTrace.MAX_EVENTS
    assert tr.to_dict()["events_dropped"] == 5


def test_timeline_store_lru():
    store = obs.TimelineStore(capacity=2)
    store.put("a", {"n": 1})
    store.put("b", {"n": 2})
    assert store.get("a") == {"n": 1}   # refreshes 'a'
    store.put("c", {"n": 3})            # evicts 'b' (LRU), not 'a'
    assert store.get("b") is None
    assert store.ids() == ["a", "c"]
    assert len(store) == 2
    with pytest.raises(ValueError):
        obs.TimelineStore(capacity=0)


# ---- flight recorder ----

def test_flight_recorder_ring_and_atomic_dump(tmp_path, monkeypatch):
    fr = obs.FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("tick", i=i)
    snap = fr.snapshot()
    assert snap["recorded"] == 6 and snap["dropped"] == 2
    assert [e["i"] for e in snap["events"]] == [2, 3, 4, 5]
    assert [e["seq"] for e in snap["events"]] == [2, 3, 4, 5]
    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    path = fr.dump(reason="unit")
    assert path == str(tmp_path / f"pdtpu_flight_{os.getpid()}.json")
    assert not os.path.exists(path + ".tmp")    # tmp renamed away
    doc = json.loads(open(path).read())
    assert doc["version"] == 1 and doc["reason"] == "unit"
    assert doc["pid"] == os.getpid()
    assert [e["i"] for e in doc["events"]] == [2, 3, 4, 5]
    # try_dump never raises, even at an unwritable path
    assert fr.try_dump(path=str(tmp_path / "no" / "dir" / "x.json")) is None
    fr.clear()
    assert fr.snapshot()["recorded"] == 0


# ---- prometheus plumbing ----

def test_prom_builder_parse_round_trip():
    b = obs.PromBuilder()
    b.family("m_total", "counter").sample("m_total", 3, labels={"k": "v"})
    b.family("g", "gauge").sample("g", 1.23456, round_to=2)
    b.sample("n", None)
    text = b.render()
    flat = obs.parse_exposition(text)
    assert flat['m_total{k="v"}'] == 3
    assert flat["g"] == 1.23
    assert np.isnan(flat["n"])


def test_training_metrics_counters_and_render():
    tm = obs.TrainingMetrics()
    tm.on_event("retry", step=3)
    tm.on_event("bad_loss", step=4)
    tm.on_event("checkpoint_save", step=4)
    tm.on_event("not_a_counter", step=9)   # unknown kinds only move step
    tm.set_step(7)
    flat = obs.parse_exposition(tm.render())
    assert flat["pdtpu_train_retries_total"] == 1
    assert flat["pdtpu_train_bad_losses_total"] == 1
    assert flat["pdtpu_train_checkpoint_saves_total"] == 1
    assert flat["pdtpu_train_rollbacks_total"] == 0
    assert flat["pdtpu_train_last_step"] == 9
    # throughput gauges ride along when a tracker is attached
    tracker = profiler.ThroughputTracker()
    tracker.update(steps=4, seconds=2.0, tokens=8)
    flat2 = obs.parse_exposition(
        obs.TrainingMetrics(tracker=tracker).render())
    assert flat2["pdtpu_train_steps_per_sec"] == 2.0
    assert flat2["pdtpu_train_total_tokens"] == 8


def test_throughput_tracker_zero_seconds_guard_and_mfu():
    # a zero-duration chunk (clock granularity) must not poison the rate
    # window; totals and last_chunk_seconds still advance
    tp = profiler.ThroughputTracker(window=4)
    tp.update(steps=2, seconds=0.0, tokens=100)
    assert tp.total_steps == 2 and tp.total_tokens == 100
    assert tp.last_chunk_seconds == 0.0
    assert tp.steps_per_sec == 0.0                 # empty window, no inf
    tp.update(steps=2, seconds=1.0, tokens=100)
    assert tp.steps_per_sec == pytest.approx(2.0)
    assert tp.last_chunk_seconds == 1.0
    s = tp.summary()
    assert s["last_chunk_seconds"] == 1.0
    assert "mfu" not in s                          # flops not registered
    assert tp.mfu is None
    # register_flops arms the windowed MFU: 2 steps/s x 1e10 / 1e12
    tp.register_flops(flops_per_step=1e10, peak_flops=1e12)
    assert tp.mfu == pytest.approx(0.02)
    assert tp.summary()["mfu"] == pytest.approx(0.02)


def test_throughput_tracker_window_aging():
    tp = profiler.ThroughputTracker(window=2)
    tp.update(steps=1, seconds=1.0)                # will age out
    tp.update(steps=4, seconds=1.0)
    tp.update(steps=4, seconds=1.0)
    assert tp.steps_per_sec == pytest.approx(4.0)  # only the last two
    assert tp.total_steps == 9                     # totals never age


def test_training_metrics_goodput_families_round_trip():
    from paddle_tpu.obs.goodput import (GoodputLedger, HBMTelemetry,
                                        RecompileSentinel)
    t = [0.0]
    led = GoodputLedger(clock=lambda: t[0])
    led.start()
    sen = RecompileSentinel(led)                   # not installed: unit feed
    with led.measure("compute"):
        t[0] += 3.0
        sen.on_compile(0.25)                       # comes out of compute
    sen.mark_warm()
    with led.measure("checkpoint"):
        t[0] += 1.0
        sen.on_compile(0.25)                       # a recompile
    led.add_steps(6)
    hbm = HBMTelemetry(stats_fn=lambda: {
        "bytes_in_use": 10, "peak_bytes_in_use": 20, "bytes_limit": 100})
    hbm.attribute("kv_slab", 7)
    tm = obs.TrainingMetrics(ledger=led, hbm=hbm, sentinel=sen)
    flat = obs.parse_exposition(tm.render())
    assert flat["pdtpu_train_goodput"] == pytest.approx(2.75 / 4.0)
    assert np.isnan(flat["pdtpu_train_mfu"])       # flops not registered
    assert flat["pdtpu_train_wall_seconds"] == pytest.approx(4.0)
    assert flat['pdtpu_train_phase_seconds_total{phase="compute"}'] == 2.75
    assert flat['pdtpu_train_phase_seconds_total{phase="checkpoint"}'] == 0.75
    assert flat['pdtpu_train_phase_seconds_total{phase="compile"}'] == 0.5
    assert flat['pdtpu_train_phase_seconds_total{phase="idle"}'] == 0.0
    assert flat["pdtpu_train_compiles_total"] == 2
    assert flat["pdtpu_train_recompiles_total"] == 1
    assert flat["pdtpu_train_compile_seconds_total"] == 0.5
    assert flat["pdtpu_train_hbm_bytes_in_use"] == 10
    assert flat["pdtpu_train_hbm_peak_bytes_in_use"] == 20
    assert flat["pdtpu_train_hbm_bytes_limit"] == 100
    assert flat['pdtpu_train_hbm_attributed_bytes{component="kv_slab"}'] == 7
    # registering flops flips the NaN to a finite gauge
    led.set_flops(1e11, 1e12)
    flat = obs.parse_exposition(tm.render())
    assert flat["pdtpu_train_mfu"] == pytest.approx(
        1e11 * 6 / 4.0 / 1e12, abs=1e-4)
    # an unavailable HBM backend just drops the hbm_* families
    tm2 = obs.TrainingMetrics(ledger=led,
                              hbm=HBMTelemetry(stats_fn=lambda: None))
    flat2 = obs.parse_exposition(tm2.render())
    assert "pdtpu_train_hbm_bytes_in_use" not in flat2
    assert "pdtpu_train_goodput" in flat2


def test_metrics_server_endpoints():
    tm = obs.TrainingMetrics()
    tm.on_event("rollback", step=2)
    srv = obs.MetricsServer([tm.render], port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(base + "/metrics")
        assert code == 200
        flat = obs.parse_exposition(body.decode())
        assert flat["pdtpu_train_rollbacks_total"] == 1
        code, body = _get(base + "/healthz")
        assert code == 200 and body == b"ok\n"
        obs.flight_recorder().record("unit_marker", n=1)
        code, body = _get(base + "/debug/flightrecorder")
        snap = json.loads(body)
        assert any(e["kind"] == "unit_marker" for e in snap["events"])
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/nope")
        assert exc.value.code == 404
    finally:
        srv.stop()


# ---- postmortem CLI (tools/flight_recorder.py) ----

def _write_dump(tmp_path):
    fr = obs.FlightRecorder()
    fr.record("reject", engine="serving", reason="queue_full", rid="r1")
    fr.record("quarantine", engine="llm", rid="deadbeef", reason="poisoned")
    return fr.dump(path=str(tmp_path / "dump.json"), reason="unit")


def _cli(*args):
    return subprocess.run([sys.executable, CLI, *args],
                          capture_output=True, text=True, cwd=REPO)


def test_flight_recorder_cli_postmortem_and_filters(tmp_path):
    dump = _write_dump(tmp_path)
    r = _cli(dump)
    assert r.returncode == 0, r.stderr
    assert "reason=unit" in r.stdout
    assert "quarantine" in r.stdout and "rid=deadbeef" in r.stdout
    r = _cli(dump, "--kind", "quarantine")
    assert r.returncode == 0
    assert "quarantine" in r.stdout and "queue_full" not in r.stdout
    r = _cli(dump, "--json")
    doc = json.loads(r.stdout)
    assert doc["reason"] == "unit" and len(doc["events"]) == 2


def test_flight_recorder_cli_merge_and_bad_file(tmp_path):
    dump = _write_dump(tmp_path)
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "step", "ph": "X", "ts": 0, "dur": 5, "pid": 0,
         "tid": 1}]}))
    out = tmp_path / "merged.json"
    r = _cli(dump, "--merge", str(trace), "-o", str(out))
    assert r.returncode == 0, r.stderr
    merged = json.loads(out.read_text())["traceEvents"]
    names = [e["name"] for e in merged]
    assert "step" in names          # original spans survive the overlay
    assert "flight/quarantine" in names and "flight/reject" in names
    inst = next(e for e in merged if e["name"] == "flight/quarantine")
    assert inst["ph"] == "i" and inst["args"]["rid"] == "deadbeef"

    bad = tmp_path / "bad.json"
    bad.write_text('{"not": "a dump"}')
    assert _cli(str(bad)).returncode == 2
    assert _cli(str(tmp_path / "missing.json")).returncode == 2


# ---- BatchingEngine tracing (threadless SimClock) ----

def test_serving_engine_traced_request_timeline():
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = serving.BatchingEngine(
        lambda args: [np.asarray(args[0]) * 2.0],
        serving.EngineConfig(max_batch_size=4, max_wait_ms=5.0),
        clock=clock)
    rid = "f00dfeed" * 4
    fut = eng.submit([np.ones((1, 3), np.float32)], rid=rid, trace=True)
    clock.advance(0.010)
    eng.pump()
    np.testing.assert_allclose(np.asarray(fut.result(timeout=0)[0]), 2.0)
    tl = eng.timelines.get(rid)
    assert tl is not None and tl["rid"] == rid
    assert tl["outcome"] == "completed"
    assert [p["name"] for p in tl["phases"]] == ["queued", "dispatch"]
    assert sum(p["dur_ms"] for p in tl["phases"]) == \
        pytest.approx(tl["latency_ms"])
    assert tl["latency_ms"] == pytest.approx(10.0)
    names = [e["name"] for e in tl["events"]]
    assert "submitted" in names and "dispatched" in names
    # untraced requests leave no timeline (and pay only a predicate)
    fut2 = eng.submit([np.ones((1, 3), np.float32)])
    clock.advance(0.010)
    eng.pump()
    fut2.result(timeout=0)
    assert len(eng.timelines) == 1
    eng.stop()


# ---- LLMEngine tracing: the reconciliation proof ----

@pytest.mark.llm
def test_llm_traced_request_timeline_reconciles(gpt_tiny):
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4),
        clock=clock)
    h = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   trace=True)
    assert h.rid and len(h.rid) == 32
    while eng.has_work():
        clock.advance(0.002)
        eng.pump()
    assert len(h.result(timeout=0)) == 4
    tl = h.timeline()
    assert tl["rid"] == h.rid and tl["outcome"] == "completed"
    assert [p["name"] for p in tl["phases"]] == ["queued", "prefill",
                                                 "decode"]
    # span-sum == latency, and the trace's TTFT boundary IS the handle's
    # ttft_ms (recorded at the same clock instant)
    assert sum(p["dur_ms"] for p in tl["phases"]) == \
        pytest.approx(tl["latency_ms"])
    assert tl["latency_ms"] > 0
    assert tl["ttft_ms"] == h.ttft_ms
    names = [e["name"] for e in tl["events"]]
    for expected in ("submitted", "admitted", "prefill_chunk",
                     "decode_step"):
        assert expected in names, names
    # the engine's LRU serves the same timeline (/debug/requests/<rid>)
    stored = eng.timelines.get(h.rid)
    assert stored["ttft_ms"] == tl["ttft_ms"]
    assert stored["outcome"] == "completed"
    eng.stop()


@pytest.mark.llm
def test_traced_request_spans_interleave_with_profiler(gpt_tiny, tmp_path):
    """The chrome export carries BOTH the pump thread's request spans
    (emitted via the process-global profiler sink) and host RecordEvent
    spans, on the same timeline."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4),
        clock=clock)
    profiler.start_profiler()
    try:
        h = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=3,
                       trace=True)
        with profiler.RecordEvent("pump_loop"):
            while eng.has_work():
                clock.advance(0.001)
                eng.pump()
        h.result(timeout=0)
    finally:
        out = tmp_path / "trace.json"
        profiler.stop_profiler(profile_path=str(out))
    eng.stop()
    events = json.load(open(out))["traceEvents"]
    names = [e["name"] for e in events]
    assert "pump_loop" in names            # RecordEvent host span
    prefix = f"req/{h.rid[:8]}/"
    req_events = [e for e in events if e["name"].startswith(prefix)]
    assert {e["ph"] for e in req_events} == {"X", "i"}
    assert any(e["name"] == prefix + "decode" and e["ph"] == "X"
               for e in req_events)


# ---- HTTP layer: traceparent propagation + debug routes ----

@pytest.mark.serving
def test_server_debug_routes_and_traced_predict():
    from paddle_tpu import serving
    W = np.random.RandomState(0).randn(3, 2).astype(np.float32)
    eng = serving.BatchingEngine(
        lambda args: [np.asarray(args[0], np.float32) @ W],
        serving.EngineConfig(max_batch_size=4, max_wait_ms=2.0))
    server = serving.ServingServer(eng, port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        tid = "0af7651916cd43dd8448eb211c80319c"
        x = np.random.RandomState(1).rand(1, 3).astype(np.float32)
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [x.tolist()]}).encode(),
            headers={"traceparent": f"00-{tid}-b7ad6b7169203331-01",
                     "X-PDTPU-Trace": "1"},
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            body = json.loads(r.read())
        np.testing.assert_allclose(body["outputs"][0], (x @ W).tolist(),
                                   rtol=1e-5, atol=1e-5)
        assert body["rid"] == tid          # traceparent trace-id propagated
        trace = body["trace"]
        assert trace["rid"] == tid and trace["outcome"] == "completed"
        assert [p["name"] for p in trace["phases"]] == ["queued",
                                                        "dispatch"]
        assert sum(p["dur_ms"] for p in trace["phases"]) == \
            pytest.approx(trace["latency_ms"])

        _, ids_body = _get(base + "/debug/requests")
        assert tid in json.loads(ids_body)["ids"]
        _, tl_body = _get(base + f"/debug/requests/{tid}")
        assert json.loads(tl_body)["rid"] == tid
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/debug/requests/" + "0" * 32)
        assert exc.value.code == 404
        _, fr_body = _get(base + "/debug/flightrecorder")
        assert json.loads(fr_body)["version"] == 1

        # untraced request: rid still echoed, no timeline kept
        req2 = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [x.tolist()]}).encode(),
            method="POST")
        with urllib.request.urlopen(req2, timeout=30) as r:
            b2 = json.loads(r.read())
        assert "trace" not in b2 and len(b2["rid"]) == 32
        assert eng.timelines.get(b2["rid"]) is None
    finally:
        server.stop()


# ---- training side: ResilientTrainer exporter ----

def test_resilient_trainer_metrics_exporter(tmp_path):
    from paddle_tpu.distributed.resilient import (ResilientConfig,
                                                  ResilientTrainer)
    from paddle_tpu.utils.fault_injection import FaultPlan

    state = {"w": 0.0}

    def train_fn(step):
        state["w"] += 1.0
        return 1.0 / (step + 1)

    t = ResilientTrainer(
        train_fn, str(tmp_path / "ckpt"),
        get_state=lambda: dict(state),
        set_state=lambda s: state.update(s),
        config=ResilientConfig(),
        fault_plan=FaultPlan.from_spec("nan_loss@2"),
        use_orbax=False, metrics_port=0)
    try:
        summary = t.run(lambda i: i, num_steps=4)
        assert summary["completed_steps"] == 4
        snap = t.metrics.snapshot()
        assert snap["bad_losses"] == 1 and snap["skips"] == 1
        assert snap["checkpoint_saves"] >= 1
        assert snap["last_step"] >= 3
        # the recovery events also landed in the black-box ring
        kinds = [e["kind"] for e in
                 obs.flight_recorder().snapshot()["events"]]
        assert "train_bad_loss" in kinds
        assert "train_checkpoint_save" in kinds
        # and the same counters are scraped over HTTP
        _, body = _get(
            f"http://127.0.0.1:{t.metrics_server.port}/metrics")
        flat = obs.parse_exposition(body.decode())
        assert flat["pdtpu_train_bad_losses_total"] == 1
        assert flat["pdtpu_train_skips_total"] == 1
        assert flat["pdtpu_train_checkpoint_saves_total"] == \
            snap["checkpoint_saves"]
        assert flat["pdtpu_train_steps_per_sec"] >= 0
    finally:
        if t.metrics_server is not None:
            t.metrics_server.stop()


# ---- the fault-matrix scenario (tools/check_fault_matrix.py) ----

@pytest.mark.llm
@pytest.mark.fault_matrix
def test_breaker_open_dump_names_quarantined_request(gpt_tiny, tmp_path,
                                                     monkeypatch):
    """Black-box contract: a breaker-open cascade leaves an atomic dump
    in PDTPU_FLIGHT_DIR that names the quarantined request id and carries
    the blame sequence — dispatch retry -> failing solo probe ->
    quarantine -> breaker open — in recorded (seq) order, readable by the
    postmortem CLI."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan

    monkeypatch.setenv(obs.DUMP_DIR_ENV, str(tmp_path))
    obs.flight_recorder().clear()
    plan = FaultPlan.from_spec(
        "poison_request@0;poison_request@2;poison_request@3")
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=2, block_len=8, n_blocks=4,
                                dispatch_retries=0, breaker_threshold=1),
        clock=serving.SimClock(), fault_plan=plan)
    # phase 1: A (idx 0) poisoned, B (idx 1) innocent -> whole-step
    # failure, solo probes blame exactly A, quarantine + absolve, B
    # completes (threshold 1 would trip on any *charged* failure, so this
    # also proves exact blame never charges the breaker)
    bad = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=3)
    good = eng.submit(np.arange(11, 15, dtype=np.int32), max_new_tokens=3)
    while eng.has_work():
        eng.pump()
    with pytest.raises(serving.DispatchFailedError, match="quarantined"):
        bad.result(timeout=0)
    assert len(good.result(timeout=0)) == 3
    assert not eng.broken
    # phase 2: C (idx 2) and D (idx 3) BOTH poisoned -> every probe fails
    # with 2 suspects -> non-attributable engine fault -> breaker opens
    c = eng.submit(np.arange(21, 25, dtype=np.int32), max_new_tokens=3)
    d = eng.submit(np.arange(31, 35, dtype=np.int32), max_new_tokens=3)
    while eng.has_work():
        eng.pump()
    for h in (c, d):
        with pytest.raises(serving.DispatchFailedError):
            h.result(timeout=0)
    assert eng.broken

    dump_path = tmp_path / f"pdtpu_flight_{os.getpid()}.json"
    assert dump_path.exists(), "breaker-open must dump the flight ring"
    assert not (tmp_path / (dump_path.name + ".tmp")).exists()
    doc = json.loads(dump_path.read_text())
    assert doc["reason"] == "breaker_open:llm"

    def seqs(kind, **match):
        return [e["seq"] for e in doc["events"] if e["kind"] == kind
                and all(e.get(k) == v for k, v in match.items())]

    # the dump NAMES the quarantined request
    q = [e for e in doc["events"] if e["kind"] == "quarantine"]
    assert len(q) == 1 and q[0]["rid"] == bad.rid
    assert q[0]["reason"] == "poisoned" and q[0]["submit_idx"] == 0
    # blame sequence in recorded order
    assert min(seqs("dispatch_retry")) < \
        min(seqs("solo_probe", rid=bad.rid, outcome="failed")) < \
        min(seqs("quarantine")) < min(seqs("breaker_open", engine="llm"))
    assert seqs("solo_probe", rid=good.rid, outcome="ok")
    assert seqs("breaker_absolved", engine="llm")   # phase 1 exonerated
    assert seqs("engine_failure", engine="llm")     # phase 2 charged
    # the postmortem CLI reads it and surfaces the rid
    r = _cli(str(dump_path))
    assert r.returncode == 0, r.stderr
    assert bad.rid in r.stdout and "breaker_open" in r.stdout
