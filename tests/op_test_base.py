"""OpTest analog (reference: python/paddle/fluid/tests/unittests/
op_test.py:270 — per-op fixtures checking kernel outputs against a NumPy
reference and analytic gradients against finite differences).

TPU adaptation: "the kernel" is the framework op running through the eager
tape on the CPU XLA backend; check_output compares against a NumPy
reference fn, check_grad compares tape gradients against central
finite differences of the op itself.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def check_output(op_fn, np_fn, inputs, atol=1e-5, rtol=1e-5):
    """op_fn(*Tensors) -> Tensor; np_fn(*ndarrays) -> ndarray."""
    tensors = [paddle.to_tensor(a) for a in inputs]
    got = op_fn(*tensors)
    want = np_fn(*inputs)
    np.testing.assert_allclose(np.asarray(got.data), want, atol=atol,
                               rtol=rtol)


def check_grad(op_fn, inputs, grad_inputs=None, delta=1e-3, atol=5e-3,
               rtol=5e-3, loss_weights=None):
    """Analytic (tape) grads vs central finite differences.

    grad_inputs: indices of inputs to differentiate (default: all).
    The scalar loss is sum(op(*) * W) with a fixed random W so every output
    element contributes a distinct weight (catches transposed/mis-scaled
    grads that a plain sum would miss).
    """
    inputs = [np.asarray(a, np.float64).astype(np.float32) for a in inputs]
    if grad_inputs is None:
        grad_inputs = range(len(inputs))

    rng = np.random.RandomState(7)
    out_probe = op_fn(*[paddle.to_tensor(a) for a in inputs])
    W = (loss_weights if loss_weights is not None
         else np.asarray(
             rng.randn(*np.asarray(out_probe.data).shape), np.float32))

    def scalar_loss(arrays):
        t = [paddle.to_tensor(a) for a in arrays]
        for i in grad_inputs:
            t[i].stop_gradient = False
        out = op_fn(*t)
        loss = paddle.sum(out * paddle.to_tensor(W))
        return loss, t

    # analytic
    loss, t = scalar_loss(inputs)
    loss.backward()
    analytic = {i: np.asarray(t[i].grad.data) for i in grad_inputs}

    # numeric: central differences on the scalar loss
    for i in grad_inputs:
        flat = inputs[i].reshape(-1)
        num = np.zeros_like(flat)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + delta
            lp = float(scalar_loss(inputs)[0].item())
            flat[j] = orig - delta
            lm = float(scalar_loss(inputs)[0].item())
            flat[j] = orig
            num[j] = (lp - lm) / (2 * delta)
        np.testing.assert_allclose(
            analytic[i].reshape(-1), num, atol=atol, rtol=rtol,
            err_msg=f"gradient mismatch for input {i}")
