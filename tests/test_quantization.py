"""Quantization: QAT fake-quant layers + PTQ calibration (slim analog)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (FakeQuantAbsMax, ImperativeQuantAware,
                                     MovingAverageAbsMaxObserver,
                                     PostTrainingQuantization, QuantedLayer,
                                     cal_kl_threshold, dequantize_weight,
                                     fake_quant_dequant, quantize_weight)


def test_fake_quant_dequant_grid_and_error_bound():
    scale = jnp.float32(2.0)
    x = jnp.linspace(-2.0, 2.0, 101)
    y = fake_quant_dequant(x, scale, 8)
    # max quantization error is half a quantization step
    step = 2.0 / 127
    assert float(jnp.max(jnp.abs(y - x))) <= step / 2 + 1e-7
    # grid values survive exactly
    grid = jnp.asarray([0.0, 2.0 / 127 * 5, -2.0 / 127 * 100])
    np.testing.assert_allclose(np.asarray(fake_quant_dequant(grid, scale, 8)),
                               np.asarray(grid), atol=1e-7)


def test_fake_quant_straight_through_gradient():
    scale = jnp.float32(1.0)
    g = jax.grad(lambda x: jnp.sum(fake_quant_dequant(x, scale, 8)))(
        jnp.asarray([0.5, -0.3, 1.5, -2.0]))
    np.testing.assert_allclose(np.asarray(g), [1.0, 1.0, 0.0, 0.0])


def test_quantize_weight_roundtrip_per_channel():
    rng = np.random.RandomState(0)
    w = rng.randn(16, 8).astype(np.float32)
    q, scale = quantize_weight(w, channel_wise=True, channel_axis=-1)
    assert q.dtype == np.int8 and scale.shape == (8,)
    wdq = dequantize_weight(q, scale, channel_axis=-1)
    step = scale / 127
    assert np.all(np.abs(wdq - w) <= step[None, :] / 2 + 1e-7)


def test_kl_threshold_clips_outliers():
    rng = np.random.RandomState(0)
    a = np.abs(rng.randn(100000)) * 0.5
    a[:10] = 50.0  # rare outliers
    hist, _ = np.histogram(a, bins=2048, range=(0, 50.0))
    thr = cal_kl_threshold(hist, 50.0 / 2048, bits=8)
    assert thr < 25.0  # clipped well below the outlier max
    assert thr > 0.5   # but keeps the bulk of the distribution


def test_qat_swaps_layers_and_trains():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ImperativeQuantAware().quantize(model)
    swapped = [l for _, l in model.named_sublayers()
               if isinstance(l, QuantedLayer)]
    assert len(swapped) == 2
    opt = optimizer.Adam(learning_rate=1e-2,
                         parameters=model.parameters())
    x = paddle.randn([32, 8])
    y = paddle.randn([32, 4])
    losses = []
    for _ in range(15):
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]
    # the activation observers accumulated moving-average scales
    obs = [l for _, l in model.named_sublayers()
           if isinstance(l, MovingAverageAbsMaxObserver)]
    assert obs and all(float(o._scale.numpy()[0]) > 0 for o in obs)


def test_qat_output_close_to_float_model():
    paddle.seed(0)
    model = nn.Linear(16, 16)
    x = paddle.randn([4, 16])
    ref = model(x).numpy()
    qmodel = nn.Sequential(model)
    ImperativeQuantAware(
        activation_quantize_type="abs_max").quantize(qmodel)
    out = qmodel(x).numpy()
    # int8 fake-quant error stays small relative to activations
    assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max()


def test_qat_conv2d_channel_wise():
    paddle.seed(0)
    model = nn.Sequential(nn.Conv2D(3, 8, 3))
    ImperativeQuantAware(
        weight_quantize_type="channel_wise_abs_max").quantize(qmodel := model)
    x = paddle.randn([1, 3, 8, 8])
    out = qmodel(x)
    assert out.shape == [1, 8, 6, 6]


def test_qat_quantizes_attribute_style_models():
    # layers assigned as attributes (self.fc = Linear) resolve via __dict__;
    # the swap must reach them too (r2 review finding)
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 8)
            self.fc2 = nn.Linear(8, 4)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    paddle.seed(0)
    net = Net()
    ImperativeQuantAware().quantize(net)
    assert isinstance(net.fc1, QuantedLayer)  # the attribute itself
    assert isinstance(net.fc2, QuantedLayer)
    out = net(paddle.randn([2, 8]))
    assert out.shape == [2, 4]
    # observers actually saw data => the wrapper really ran
    obs = [l for _, l in net.named_sublayers()
           if isinstance(l, MovingAverageAbsMaxObserver)]
    assert all(float(o._scale.numpy()[0]) > 0 for o in obs)


def test_observer_uncalibrated_eval_passes_through():
    obs = MovingAverageAbsMaxObserver()
    obs.eval()  # never trained: scale == 0 must NOT clip to ~0
    x = paddle.randn([4, 4])
    np.testing.assert_allclose(obs(x).numpy(), x.numpy())


def test_observer_freezes_in_eval():
    obs = MovingAverageAbsMaxObserver()
    x = paddle.randn([8, 8])
    obs.train()
    obs(x)
    s1 = float(obs._scale.numpy()[0])
    assert s1 > 0
    obs.eval()
    obs(paddle.to_tensor(np.full((8, 8), 100.0, np.float32)))
    assert float(obs._scale.numpy()[0]) == s1  # frozen


def test_ptq_calibrates_and_quantizes():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = paddle.randn([16, 8])
    ref = model(x).numpy()
    rng = np.random.RandomState(0)
    calib = [rng.randn(16, 8).astype(np.float32) for _ in range(4)]
    ptq = PostTrainingQuantization(model, algo="abs_max")
    ptq.quantize(calib)
    assert len(ptq.int8_state) == 2
    assert all(v.dtype == np.int8 for v in ptq.int8_state.values())
    assert all("activation" in s and "weight" in s
               for s in ptq.scales.values())
    out = model(x).numpy()  # weights now carry baked quantization error
    assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max()


def test_ptq_kl_algo_runs():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8))
    rng = np.random.RandomState(0)
    calib = [rng.randn(8, 8).astype(np.float32) for _ in range(3)]
    ptq = PostTrainingQuantization(model, algo="KL")
    ptq.quantize(calib)
    assert list(ptq.scales.values())[0]["activation"] > 0


def test_qat_save_quantized_model_servable(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    ImperativeQuantAware().quantize(model)
    model(paddle.randn([2, 4]))  # populate observer scales
    path = str(tmp_path / "qat")
    ImperativeQuantAware().save_quantized_model(
        model, path, input_spec=[np.zeros((1, 4), np.float32)])
    from paddle_tpu import inference
    pred = inference.create_predictor(inference.Config(path))
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(np.ones((1, 4), np.float32))
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    assert out.shape == (1, 2)


from _artifact_utils import parse_pdweights_types as \
    _parse_pdweights_types  # noqa: E402


def test_ptq_int8_weights_reach_the_predictor(tmp_path):
    """VERDICT r4 item 8: the exported artifact stores INT8 weights that
    the predictor consumes (dequant happens inside the exported graph),
    and serving accuracy stays within delta of fp32."""
    import json
    from paddle_tpu import inference
    paddle.seed(0)
    model = paddle.vision.models.LeNet(num_classes=10)
    rng = np.random.RandomState(0)
    x = rng.rand(4, 1, 28, 28).astype(np.float32)
    fp32_out = model(paddle.to_tensor(x)).numpy()

    calib = [rng.rand(4, 1, 28, 28).astype(np.float32) for _ in range(3)]
    ptq = PostTrainingQuantization(model, algo="abs_max")
    ptq.quantize(calib)
    path = str(tmp_path / "lenet_int8")
    ptq.save_quantized_model(path, input_spec=[x])

    # int8 weights are IN the artifact (PDW1 type code 2), not a side file
    codes = _parse_pdweights_types(path + ".pdweights")
    assert codes.count(2) == len(ptq.int8_state) > 0
    meta = json.load(open(path + ".pdmodel.json"))
    assert len(meta["quantized"]) == len(ptq.int8_state)

    pred = inference.load_predictor(path)
    (served,) = pred.run([x])
    # served == the fake-quant-folded model (exact dequant parity) ...
    folded = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(served, folded, rtol=1e-4, atol=1e-4)
    # ... and within quantization delta of the ORIGINAL fp32 model
    assert np.abs(served - fp32_out).max() < \
        0.1 * max(np.abs(fp32_out).max(), 1e-6)
    # top-1 agreement on every calibrated-distribution sample
    np.testing.assert_array_equal(served.argmax(-1), fp32_out.argmax(-1))


def test_qat_export_stores_int8(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    ImperativeQuantAware().quantize(model)
    x = paddle.randn([4, 8])
    model(x)  # calibrate observers
    eager = model(x).numpy()
    path = str(tmp_path / "qat_int8")
    ImperativeQuantAware().save_quantized_model(
        model, path, input_spec=[x.numpy()])
    codes = _parse_pdweights_types(path + ".pdweights")
    assert codes.count(2) == 2  # both Linear weights int8
    from paddle_tpu import inference
    pred = inference.load_predictor(path)
    (served,) = pred.run([x.numpy()])
    np.testing.assert_allclose(served, eager, rtol=1e-3, atol=1e-3)


def test_qat_4bit_export_uses_layer_grid(tmp_path):
    """A 4-bit-trained QAT model must export on ITS grid even when the
    exporting driver instance is a default (8-bit) one."""
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 4))
    ImperativeQuantAware(weight_bits=4).quantize(model)
    x = paddle.randn([4, 8])
    model(x)
    eager = model(x).numpy()
    path = str(tmp_path / "qat4")
    # note: DEFAULT driver instance does the export
    ImperativeQuantAware().save_quantized_model(
        model, path, input_spec=[x.numpy()])
    import json
    meta = json.load(open(path + ".pdmodel.json"))
    assert all(v["bits"] == 4 for v in meta["quantized"].values())
    from paddle_tpu import inference
    pred = inference.load_predictor(path)
    (served,) = pred.run([x.numpy()])
    np.testing.assert_allclose(served, eager, rtol=1e-3, atol=1e-3)
