"""paddle.fluid compat layer: a fluid-era dygraph training script runs
unmodified (reference python/paddle/fluid surface — guard/to_variable,
layers.fc/conv2d/pool2d/cross_entropy with legacy signatures,
*Optimizer classes with parameter_list, legacy initializer/regularizer
names)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


def test_fluid_dygraph_training_script():
    """The canonical fluid-era mnist-style loop, verbatim idioms."""
    rng = np.random.RandomState(0)

    class Net(fluid.dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.conv = fluid.dygraph.Conv2D(1, 4, 3, padding=1)
            self.fc = fluid.dygraph.Linear(4 * 4 * 4, 10)

        def forward(self, x):
            h = fluid.layers.relu(self.conv(x))
            h = fluid.layers.pool2d(h, 2, "max", 2)
            h = fluid.layers.reshape(h, [h.shape[0], -1])
            return self.fc(h)

    with fluid.dygraph.guard():
        net = Net()
        opt = fluid.optimizer.AdamOptimizer(
            learning_rate=1e-3, parameter_list=net.parameters())
        losses = []
        x = fluid.dygraph.to_variable(
            rng.randn(8, 1, 8, 8).astype(np.float32))
        y = fluid.dygraph.to_variable(rng.randint(0, 10, (8,)))
        for _ in range(5):
            logits = net(x)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y.unsqueeze(-1)))
            loss.backward()
            opt.minimize(loss)
            net.clear_gradients()
            losses.append(float(loss.item()))
        assert losses[-1] < losses[0]


def test_fluid_layers_legacy_signatures():
    rng = np.random.RandomState(1)
    x = fluid.dygraph.to_variable(rng.randn(2, 3, 5).astype(np.float32))
    # fc flattens trailing dims per num_flatten_dims
    out = fluid.layers.fc(x, 4, num_flatten_dims=1)
    assert np.asarray(out.data).shape == (2, 4)
    out2 = fluid.layers.fc(x, 4, num_flatten_dims=2)
    assert np.asarray(out2.data).shape == (2, 3, 4)
    # embedding with size pair
    ids = fluid.dygraph.to_variable(np.array([[0, 2], [1, 3]]))
    emb = fluid.layers.embedding(ids, size=[10, 6])
    assert np.asarray(emb.data).shape == (2, 2, 6)
    # fill_constant / assign / cast
    c = fluid.layers.fill_constant([2, 2], "float32", 3.0)
    assert float(c.sum().item()) == 12.0
    d = fluid.layers.cast(c, "int32")
    assert str(d.dtype) == "int32"
    # elementwise axis broadcast
    e = fluid.layers.elementwise_mul(
        fluid.dygraph.to_variable(np.ones((2, 3, 4), np.float32)),
        fluid.dygraph.to_variable(np.full(3, 2.0, np.float32)), axis=1)
    assert float(e.sum().item()) == 48.0
    # cross_entropy over PROBABILITIES (the fluid op contract)
    probs = fluid.dygraph.to_variable(
        np.array([[0.7, 0.3], [0.2, 0.8]], np.float32))
    lbl = fluid.dygraph.to_variable(np.array([0, 1]))
    ce = np.asarray(fluid.layers.cross_entropy(probs, lbl).data)
    np.testing.assert_allclose(ce, -np.log([0.7, 0.8]), atol=1e-5)


def test_fluid_optimizer_and_attr_names():
    net = fluid.dygraph.Linear(4, 2)
    opt = fluid.optimizer.MomentumOptimizer(
        learning_rate=0.1, momentum=0.9, parameter_list=net.parameters(),
        regularization=fluid.regularizer.L2DecayRegularizer(1e-4))
    x = fluid.dygraph.to_variable(np.ones((2, 4), np.float32))
    loss = fluid.layers.mean(net(x))
    loss.backward()
    opt.minimize(loss)
    w = fluid.layers.create_parameter(
        [3, 3], "float32",
        default_initializer=fluid.initializer.MSRA())
    assert np.asarray(w.data).std() > 0
    assert fluid.in_dygraph_mode()


def test_fluid_static_facade_roundtrip(tmp_path):
    prog = fluid.Program()
    assert fluid.default_main_program() is not None
    with fluid.program_guard(prog):
        pass
    exe = fluid.Executor()
    spec = fluid.layers.data("x", [4], "float32")
    assert list(spec.shape) == [-1, 4]


def test_fluid_renamed_equivalents():
    """fluid names mapped onto renamed modern ops keep the FLUID
    conventions (lrn's sum-scaled alpha, hard_sigmoid's 0.2 slope,
    resize_* wrappers)."""
    rng = np.random.RandomState(2)
    x = fluid.dygraph.to_variable(rng.randn(1, 6, 3, 3).astype(np.float32))
    ours = np.asarray(fluid.layers.lrn(x, n=3, alpha=1e-3).data)
    xl = np.asarray(x.data)
    sq = np.pad(xl ** 2, ((0, 0), (1, 1), (0, 0), (0, 0)))
    acc = sq[:, :6] + sq[:, 1:7] + sq[:, 2:8]
    np.testing.assert_allclose(ours, xl / (1 + 1e-3 * acc) ** 0.75,
                               atol=1e-5)
    hs = fluid.layers.hard_sigmoid(
        fluid.dygraph.to_variable(np.zeros(1, np.float32)))
    assert abs(float(hs.item()) - 0.5) < 1e-6
    img = fluid.dygraph.to_variable(rng.randn(1, 2, 4, 4).astype(np.float32))
    assert np.asarray(fluid.layers.image_resize(
        img, out_shape=[8, 8], resample="NEAREST").data).shape == \
        (1, 2, 8, 8)
    p = fluid.layers.pad2d(img, [1, 1, 2, 2], mode="reflect")
    assert np.asarray(p.data).shape == (1, 2, 6, 8)
    assert hasattr(fluid.layers, "yolo_box")
    assert hasattr(fluid.layers, "multiclass_nms")


def test_fluid_interp_and_loss_conventions():
    """The fluid-specific numeric conventions: align_mode=1 asymmetric
    resize, nearest corner rounding, seeded gaussian, hard_swish params,
    smooth_l1 sigma/weights, in-place relu_."""
    rng = np.random.RandomState(0)
    x = rng.randn(1, 1, 4, 4).astype(np.float32)
    out = np.asarray(fluid.layers.resize_bilinear(
        fluid.dygraph.to_variable(x), out_shape=[8, 8],
        align_corners=False).data)
    src = np.arange(8) * (4 / 8)
    lo = np.floor(src).astype(int)
    hi = np.minimum(lo + 1, 3)
    w = src - lo
    tmp = x[0, 0][lo] * (1 - w[:, None]) + x[0, 0][hi] * w[:, None]
    want = tmp[:, lo] * (1 - w[None, :]) + tmp[:, hi] * w[None, :]
    np.testing.assert_allclose(out[0, 0], want, atol=1e-5)
    a = np.asarray(fluid.layers.gaussian_random([4], seed=5).data)
    b = np.asarray(fluid.layers.gaussian_random([4], seed=5).data)
    np.testing.assert_array_equal(a, b)
    xs = fluid.dygraph.to_variable(np.array([[0.1, 2.0]], np.float32))
    ys = fluid.dygraph.to_variable(np.zeros((1, 2), np.float32))
    iw = fluid.dygraph.to_variable(np.ones((1, 2), np.float32))
    ow = fluid.dygraph.to_variable(np.full((1, 2), 2.0, np.float32))
    sl = float(fluid.layers.smooth_l1(xs, ys, iw, ow, sigma=3.0).item())
    want_sl = 2 * (0.5 * 0.01 * 9.0) + 2 * (2.0 - 0.5 / 9.0)
    assert abs(sl - want_sl) < 1e-5
    t = fluid.dygraph.to_variable(np.array([-1.0, 2.0], np.float32))
    fluid.layers.relu_(t)
    np.testing.assert_allclose(np.asarray(t.data), [0.0, 2.0])


# ---- legacy transpiler (distribute_transpiler.py:256 facade) ----

def test_distribute_transpiler_pserver_trainer_roundtrip():
    """The 1.x PS deployment script shape: transpile -> run pserver
    programs -> trainer program pulls/pushes across both shards."""
    import numpy as np
    from paddle_tpu import fluid

    config = fluid.DistributeTranspilerConfig()
    config.slice_var_up = False
    t = fluid.DistributeTranspiler(config=config)
    # port 0 is not usable for the endpoint list (the trainer must know
    # the ports); reserve two via the shared launch helper
    from paddle_tpu.distributed.utils import find_free_ports
    eps = [f"127.0.0.1:{p}" for p in sorted(find_free_ports(2))]
    t.transpile(trainer_id=0, pservers=",".join(eps), trainers=1)

    servers = []
    try:
        for ep in eps:
            prog, startup = t.get_pserver_programs(ep)
            startup.run()
            servers.append(prog.run())
        trainer = t.get_trainer_program()
        trainer.create_table("emb", 4, rule="sgd", lr=0.5, init_std=0.0)
        ids = np.arange(8)
        trainer.pull_sparse("emb", ids)
        trainer.push_sparse("emb", ids, np.ones((8, 4), np.float32))
        out = trainer.pull_sparse("emb", ids)
        np.testing.assert_allclose(out, -0.5, atol=1e-6)
    finally:
        for s in servers:
            s.stop()


def test_transpiler_dispatchers_and_guards():
    from paddle_tpu.fluid.transpiler import HashName, RoundRobin
    import pytest as _pytest
    from paddle_tpu import fluid

    eps = ["a:1", "b:2", "c:3"]
    rr = RoundRobin(eps)
    assert rr.dispatch([1, 2, 3, 4]) == ["a:1", "b:2", "c:3", "a:1"]
    rr.reset()
    assert rr.dispatch([1]) == ["a:1"]

    class V:
        def __init__(self, name):
            self.name = name

    hn = HashName(eps)
    d1 = hn.dispatch([V("w1"), V("w2"), V("w1")])
    assert d1[0] == d1[2]  # deterministic by name

    t = fluid.DistributeTranspiler()
    with _pytest.raises(RuntimeError, match="transpile"):
        t.get_trainer_program()
    t.transpile(0, pservers="127.0.0.1:7777")
    with _pytest.raises(ValueError, match="not one of"):
        t.get_pserver_program("127.0.0.1:9999")
