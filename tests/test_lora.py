"""Multi-LoRA fine-tune-and-serve loop (ISSUE 20).

Contracts under test. **Tuning:** `inject_lora` freezes every base
parameter bitwise and trains ONLY the low-rank adapter leaves — a CPU
fine-tune moves the loss while the base weights stay byte-identical,
and `functional_state()` yields an adapter-only params tree (what the
async checkpoint ring snapshots during LoRA fine-tuning). **Serving:**
the `AdapterBank` threads K stacked adapter trees through the ONE
fixed-width jitted unified step via a per-slot `adapter_idx` lane —
`adapter=None` slots ride the all-zeros row 0 bit-identical to the
pre-LoRA engine, a mixed batch of several adapters matches each
adapter's solo decode token-for-token, and adapter load/hot-swap/unload
never recompiles. **Isolation & lifecycle:** per-adapter KV namespaces
`(tenant, adapter)`, typed admission refusals, adapter-scoped fault
blame, hot-swap canary with fleet auto-rollback, and failover that
restores the adapter on the survivor bit-identically.

Scheduler tests drive the PRODUCTION pump under a SimClock. The
heavyweight end-to-end scenarios (fine-tune loop, fleet rollouts,
fault-matrix rows) are `slow`-marked to keep tier-1 inside its time
budget — `tools/check_fault_matrix.py` collects and runs them by the
`fault_matrix` marker regardless."""
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def gpt_tiny():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    paddle.seed(0)
    return GPTForCausalLM.from_preset("gpt2-tiny")


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    from paddle_tpu.utils.fault_injection import set_global_plan
    set_global_plan(None)
    yield
    set_global_plan(None)


def _mk_tree(model, seed, rank=4, scale=0.3):
    """A synthetic adapter in the bank's canonical layout: random A AND
    nonzero B (a fresh-trained adapter has B=0 → zero delta; tests need
    deltas that actually flip greedy tokens)."""
    from paddle_tpu.tuning import target_sites
    sites, _arch = target_sites(model)
    r = np.random.RandomState(seed)
    return {
        str(i): {name: {"A": (scale * r.randn(rank, io[0])
                              ).astype(np.float32),
                        "B": (scale * r.randn(io[1], rank)
                              ).astype(np.float32)}
                 for name, io in layer.items()}
        for i, layer in enumerate(sites)}


def _armed(gpt_tiny, clock, **cfg_kw):
    from paddle_tpu import serving
    kw = dict(num_slots=4, block_len=8, n_blocks=8, max_queue_depth=64,
              max_adapters=3, lora_rank=4)
    kw.update(cfg_kw)
    return serving.LLMEngine(gpt_tiny, serving.LLMEngineConfig(**kw),
                             clock=clock)


def _drive(eng, clock, dt=0.01, max_steps=2000):
    steps = 0
    while eng.has_work():
        clock.advance(dt)
        eng.pump()
        steps += 1
        assert steps < max_steps, "engine failed to converge"


def _drive_router(router, clock, dt=0.01, max_steps=4000):
    steps = 0
    while router.has_work():
        clock.advance(dt)
        router.pump()
        steps += 1
        assert steps < max_steps, "router failed to converge"


def _reference(gpt_tiny, prompt, max_new_tokens):
    from paddle_tpu.models.generation import generate
    out = np.asarray(generate(gpt_tiny, np.asarray(prompt)[None, :],
                              max_new_tokens=max_new_tokens))
    return out[0, np.asarray(prompt).size:]


def _solo_adapter_decode(gpt_tiny, clock, tree, prompt, max_new, aid="solo"):
    """Oracle: a fresh armed engine decoding ONE stream through `tree`."""
    eng = _armed(gpt_tiny, clock)
    eng.register_adapter(aid, tree)
    h = eng.submit(np.asarray(prompt, np.int32), max_new_tokens=max_new,
                   adapter=aid)
    _drive(eng, clock)
    return h.result(timeout=0)


# ---- tuning: train the adapter, freeze the base ----

@pytest.mark.slow
def test_lora_finetune_moves_loss_base_bitwise_frozen():
    """A few SGD steps on `lora_parameters` reduce the causal-LM loss;
    every base weight is BITWISE untouched (frozen, not merely small-
    gradient), and only lora_A/lora_B moved."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models.gpt import GPTForCausalLM
    from paddle_tpu.tuning import LoRAConfig, inject_lora, lora_parameters

    paddle.seed(7)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    base_before = {n: np.array(p.numpy(), copy=True)
                   for n, p in model.named_parameters()}
    inject_lora(model, LoRAConfig(rank=4, alpha=8.0))
    params = lora_parameters(model)
    assert params and all(p.trainable for p in params)

    opt = optimizer.SGD(learning_rate=0.1, parameters=params)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(
        1, model.config.vocab_size, size=(2, 8)).astype(np.int64))
    labels = paddle.to_tensor(rng.randint(
        1, model.config.vocab_size, size=(2, 8)).astype(np.int64))
    losses = []
    for _ in range(3):
        loss = model(x, labels=labels)
        losses.append(float(loss.numpy()))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert losses[-1] < losses[0], losses

    moved = 0
    for n, p in model.named_parameters():
        cur = np.asarray(p.numpy())
        if "lora_" in n:
            if not np.array_equal(cur, np.zeros_like(cur)):
                moved += 1
            continue
        # injection re-homes a wrapped Linear's params under `.base.`
        key = n.replace(".base.", ".") if n.replace(".base.", ".") in \
            base_before else n
        np.testing.assert_array_equal(
            cur, base_before[key], err_msg=f"base weight {n} moved")
    assert moved > 0, "no adapter leaf moved during fine-tune"


def test_adapter_state_roundtrip_and_signature():
    """adapter_state_dict → load_adapter_state is bitwise; the signature
    pins arch/layers/rank/targets/dims."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    from paddle_tpu.tuning import (LoRAConfig, adapter_signature,
                                   adapter_state_dict, inject_lora,
                                   load_adapter_state)

    paddle.seed(3)
    m1 = GPTForCausalLM.from_preset("gpt2-tiny")
    inject_lora(m1, LoRAConfig(rank=4))
    # give the adapter nonzero content so the round trip is meaningful
    rng = np.random.RandomState(1)
    for _, p in m1.named_parameters():
        if p.trainable:
            p.set_value(rng.randn(*p.shape).astype(np.float32))
    tree = adapter_state_dict(m1)

    paddle.seed(3)
    m2 = GPTForCausalLM.from_preset("gpt2-tiny")
    inject_lora(m2, LoRAConfig(rank=4))
    load_adapter_state(m2, tree)
    for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                  m2.named_parameters()):
        assert n1 == n2
        np.testing.assert_array_equal(p1.numpy(), p2.numpy())

    sig = adapter_signature(m1, 4)
    assert sig["arch"] == "gpt" and sig["rank"] == 4
    assert sig["num_layers"] == len(tree)
    assert sorted(sig["targets"]) == sorted(next(iter(tree.values())))


def test_functional_state_params_are_adapter_only():
    """The async-checkpoint pin: after inject_lora, `functional_state()`
    params = ONLY the trainable lora leaves (2 per site per layer), so
    the snapshot ring copies kilobytes, not the base model."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForCausalLM
    from paddle_tpu.tuning import LoRAConfig, inject_lora, target_sites

    paddle.seed(5)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    inject_lora(model, LoRAConfig(rank=4))
    sites, _ = target_sites(model)
    params, buffers = model.functional_state()
    assert len(params) == 2 * sum(len(s) for s in sites)
    assert all("lora_" in k for k in params)
    assert buffers, "base weights must ride the buffers tree"


# ---- serving: the bank in the unified step ----

@pytest.mark.lora
def test_base_slots_bit_identical_on_armed_engine(gpt_tiny):
    """adapter=None streams on a bank-armed engine ride row 0 (exact-
    zero delta) and match the pre-LoRA greedy generate() bitwise."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _armed(gpt_tiny, clock)
    eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1))  # bank non-empty
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 500, size=(6,)).astype(np.int32)
               for _ in range(3)]
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
    _drive(eng, clock)
    for p, h in zip(prompts, handles):
        np.testing.assert_array_equal(h.result(timeout=0),
                                      _reference(gpt_tiny, p, 8))
    eng.stop()


@pytest.mark.lora
@pytest.mark.slow
def test_mixed_adapter_batch_matches_solo_decode(gpt_tiny):
    """One dispatch-width batch mixing base + 2 different adapters over
    the SAME prompt: every stream matches its solo-decode oracle
    token-for-token (the gathered per-row delta never bleeds across
    slots), and the adapter streams actually diverge from base."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    trees = {f"ad{i}": _mk_tree(gpt_tiny, i) for i in (1, 2)}
    prompt = np.random.RandomState(4).randint(
        1, 500, size=(6,)).astype(np.int32)

    solo = {aid: _solo_adapter_decode(gpt_tiny, clock, t, prompt, 8,
                                      aid=aid)
            for aid, t in trees.items()}

    eng = _armed(gpt_tiny, clock)
    for aid, t in trees.items():
        eng.register_adapter(aid, t)
    hb = eng.submit(prompt, max_new_tokens=8)
    ha = {aid: eng.submit(prompt, max_new_tokens=8, adapter=aid)
          for aid in trees}
    _drive(eng, clock)
    base_out = hb.result(timeout=0)
    np.testing.assert_array_equal(base_out, _reference(gpt_tiny, prompt, 8))
    diverged = 0
    for aid in trees:
        out = ha[aid].result(timeout=0)
        np.testing.assert_array_equal(
            out, solo[aid], err_msg=f"{aid}: mixed != solo")
        diverged += int(not np.array_equal(out, base_out))
    assert diverged > 0, "no adapter changed a single greedy token"
    eng.stop()


@pytest.mark.lora
def test_adapter_churn_zero_recompiles(gpt_tiny):
    """Register / hot-swap / unload adapters across decode waves: the
    bank only rewrites operand VALUES, so the warm unified-step
    executable is reused — zero post-warmup recompiles."""
    from paddle_tpu import serving
    from paddle_tpu.obs.compile_observatory import compile_observatory
    obs = compile_observatory()
    obs.reset()
    try:
        clock = serving.SimClock()
        eng = _armed(gpt_tiny, clock, observatory=True)
        eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1))
        prompt = np.arange(1, 7, dtype=np.int32)
        h = eng.submit(prompt, max_new_tokens=4, adapter="ad1")
        _drive(eng, clock)
        h.result(timeout=0)
        obs.mark_warm()

        eng.register_adapter("ad2", _mk_tree(gpt_tiny, 2))   # fresh load
        eng.register_adapter("ad1", _mk_tree(gpt_tiny, 9))   # hot swap
        hs = [eng.submit(prompt, max_new_tokens=4, adapter=a)
              for a in ("ad1", "ad2", None)]
        _drive(eng, clock)
        for h in hs:
            assert h.result(timeout=0).size == 4
        eng.unregister_adapter("ad2")
        h = eng.submit(prompt, max_new_tokens=4, adapter="ad1")
        _drive(eng, clock)
        h.result(timeout=0)
        assert obs.recompiles == 0
        eng.stop()
    finally:
        obs.reset()


@pytest.mark.lora
def test_adapter_kv_namespaces_probe_and_scoped_flush(gpt_tiny):
    """Prefix KV is keyed `(tenant, adapter)`: an adapter's warm blocks
    never serve base (or another adapter's) admissions, and a hot swap
    flushes EXACTLY that adapter's namespaces — base stays warm."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _armed(gpt_tiny, clock, block_len=4, n_blocks=16)
    eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1))
    eng.register_adapter("ad2", _mk_tree(gpt_tiny, 2))
    prompt = np.arange(1, 14, dtype=np.int32)   # 3 full blocks + tail
    for ad in (None, "ad1", "ad2"):
        h = eng.submit(prompt, max_new_tokens=2, adapter=ad,
                       tenant="acme")
        _drive(eng, clock)
        h.result(timeout=0)
    assert eng.prefix_probe(prompt, tenant="acme") > 0
    assert eng.prefix_probe(prompt, tenant="acme", adapter="ad1") > 0
    assert eng.prefix_probe(prompt, tenant="acme", adapter="ad2") > 0
    # namespaces don't alias: an unknown adapter id probes cold
    assert eng.prefix_probe(prompt, tenant="acme", adapter="other") == 0

    eng.register_adapter("ad1", _mk_tree(gpt_tiny, 9))   # hot swap
    assert eng.prefix_probe(prompt, tenant="acme", adapter="ad1") == 0, \
        "swapped adapter's stale KV must be flushed"
    assert eng.prefix_probe(prompt, tenant="acme") > 0, \
        "base namespace must survive an adapter swap"
    assert eng.prefix_probe(prompt, tenant="acme", adapter="ad2") > 0, \
        "sibling adapter's namespace must survive the swap"
    eng.stop()


@pytest.mark.lora
def test_typed_adapter_rejects(gpt_tiny):
    """Admission and lifecycle refusals are typed: adapter_unavailable
    (no bank), unknown_adapter, bank_full, rank_mismatch, and
    adapter_in_use on unregister with live streams."""
    from paddle_tpu import serving
    from paddle_tpu.serving.llm.lora import AdapterError
    clock = serving.SimClock()
    prompt = np.arange(1, 5, dtype=np.int32)

    plain = serving.LLMEngine(
        gpt_tiny, serving.LLMEngineConfig(num_slots=2, block_len=8,
                                          n_blocks=4), clock=clock)
    with pytest.raises(serving.RejectedError) as exc:
        plain.submit(prompt, max_new_tokens=2, adapter="ad1")
    assert exc.value.reason == "adapter_unavailable"

    eng = _armed(gpt_tiny, clock, max_adapters=1)
    with pytest.raises(serving.RejectedError) as exc:
        eng.submit(prompt, max_new_tokens=2, adapter="nope")
    assert exc.value.reason == "unknown_adapter"

    eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1))
    with pytest.raises(AdapterError) as aexc:
        eng.register_adapter("ad2", _mk_tree(gpt_tiny, 2))
    assert aexc.value.reason == "bank_full"
    with pytest.raises(AdapterError) as aexc:
        eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1, rank=8))
    assert aexc.value.reason in ("rank_mismatch", "adapter_mismatch")

    h = eng.submit(prompt, max_new_tokens=16, adapter="ad1")
    clock.advance(0.01)
    eng.pump()                      # stream is now live on the row
    with pytest.raises(AdapterError) as aexc:
        eng.unregister_adapter("ad1")
    assert aexc.value.reason == "adapter_in_use"
    _drive(eng, clock)
    h.result(timeout=0)
    eng.unregister_adapter("ad1")   # idle now: unload succeeds
    assert eng.adapter_bank.row_of("ad1") is None
    eng.stop()


# ---- fault matrix ----

@pytest.mark.lora
@pytest.mark.slow
@pytest.mark.fault_matrix
def test_poisoned_adapter_stream_quarantined_without_evicting_others(
        gpt_tiny):
    """poison_request@1:adapter fires only on adapter-kind dispatches
    carrying submit-index 1: that ONE adapter stream is quarantined
    (typed 'poisoned') while the co-scheduled base stream and the
    OTHER adapter's stream finish bit-identical to their oracles."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan

    clock = serving.SimClock()
    trees = {"ad1": _mk_tree(gpt_tiny, 1), "ad2": _mk_tree(gpt_tiny, 2)}
    prompt = np.random.RandomState(6).randint(
        1, 500, size=(6,)).astype(np.int32)
    solo2 = _solo_adapter_decode(gpt_tiny, clock, trees["ad2"], prompt, 6,
                                 aid="ad2")

    plan = FaultPlan.from_spec("poison_request@1:adapter")
    eng = serving.LLMEngine(
        gpt_tiny,
        serving.LLMEngineConfig(num_slots=4, block_len=8, n_blocks=8,
                                max_queue_depth=64, max_adapters=3,
                                lora_rank=4),
        clock=clock, fault_plan=plan)
    for aid, t in trees.items():
        eng.register_adapter(aid, t)
    base = eng.submit(prompt, max_new_tokens=6)                 # idx 0
    poisoned = eng.submit(prompt, max_new_tokens=6, adapter="ad1")  # 1
    other = eng.submit(prompt, max_new_tokens=6, adapter="ad2")     # 2
    _drive(eng, clock)

    with pytest.raises(serving.DispatchFailedError) as exc:
        poisoned.result(timeout=0)
    assert exc.value.reason == "poisoned"
    np.testing.assert_array_equal(base.result(timeout=0),
                                  _reference(gpt_tiny, prompt, 6))
    np.testing.assert_array_equal(other.result(timeout=0), solo2)
    snap = eng.metrics.snapshot()
    assert snap["quarantined"] == 1 and snap["completed"] == 2
    assert not eng.broken
    eng.pool.check_balance()
    eng.stop()


@pytest.mark.lora
@pytest.mark.slow
@pytest.mark.fault_matrix
def test_nan_adapter_swap_canary_rolls_back_fleet(gpt_tiny, tmp_path,
                                                  monkeypatch):
    """A NaN-poisoned (yet CRC-certified) adapter hot-swap is caught by
    the per-replica adapter canary and the fleet auto-rolls the row
    back: `adapter_swap` precedes `adapter_rollback` per replica in the
    flight record, streams admitted before the rollout finish on the
    ORIGINAL adapter bit-identically (zero dropped), and base weights
    were never touched. A good set then rolls out cleanly on the SAME
    fleet (no drain, canary on both replicas, record `completed`)."""
    from paddle_tpu import serving
    from paddle_tpu.checkpoint import AdapterWeightSet
    from paddle_tpu.obs.flight_recorder import flight_recorder

    monkeypatch.setenv("PDTPU_FLIGHT_DIR", str(tmp_path))
    flight_recorder().clear()
    clock = serving.SimClock()
    good = _mk_tree(gpt_tiny, 1)
    prompt = np.random.RandomState(8).randint(
        1, 500, size=(6,)).astype(np.int32)
    solo = _solo_adapter_decode(gpt_tiny, clock, good, prompt, 8)

    reps = [serving.InProcessReplica(_armed(gpt_tiny, clock), i)
            for i in range(2)]
    router = serving.ReplicaRouter(reps)
    for r in reps:
        r.engine.register_adapter("helpdesk", good)

    # in-flight adapter + base streams that must survive the rollout
    h_ad = router.submit(prompt, max_new_tokens=8, adapter="helpdesk")
    h_b = router.submit(prompt, max_new_tokens=8)
    for _ in range(3):
        clock.advance(0.01)
        router.pump()
    assert len(h_ad.tokens_so_far()) > 0

    bad = {li: {s: {"A": np.full_like(e["A"], np.nan), "B": e["B"]}
                for s, e in layer.items()}
           for li, layer in good.items()}
    sig = reps[0].engine.adapter_bank.signature
    ws = AdapterWeightSet.publish(str(tmp_path), "helpdesk-v2", bad, sig)
    ctrl = serving.DeploymentController(
        router, serving.DeployConfig(watch_window_s=0.01))
    rec = ctrl.deploy_adapter(ws, adapter_id="helpdesk")
    assert rec["outcome"] == "rolled_back"
    assert rec["reason"].startswith("nonfinite_logits")

    _drive_router(router, clock)
    np.testing.assert_array_equal(h_ad.result(timeout=0), solo)
    np.testing.assert_array_equal(h_b.result(timeout=0),
                                  _reference(gpt_tiny, prompt, 8))
    # the restored row still serves the ORIGINAL delta
    h2 = router.submit(prompt, max_new_tokens=8, adapter="helpdesk")
    _drive_router(router, clock)
    np.testing.assert_array_equal(h2.result(timeout=0), solo)

    events = flight_recorder().snapshot()["events"]
    kinds = [e["kind"] for e in events]
    assert "adapter_deploy_started" in kinds
    assert "adapter_deploy_rollback" in kinds
    swaps = [i for i, e in enumerate(events)
             if e["kind"] == "adapter_swap" and e.get("update")]
    rollbacks = [i for i, e in enumerate(events)
                 if e["kind"] == "adapter_rollback"]
    assert swaps and rollbacks
    assert min(swaps) < min(rollbacks), \
        "swap must precede rollback in the flight record"
    assert len(rollbacks) == len(swaps)

    # happy path on the same fleet: the SAME good tree published as a
    # certified set rolls out under a fresh adapter id with no drain,
    # and decodes bit-identical to the solo oracle on both replicas
    ws2 = AdapterWeightSet.publish(str(tmp_path), "summarize-v1", good,
                                   sig)
    rec2 = ctrl.deploy_adapter(ws2)
    assert rec2["outcome"] == "completed"
    assert sorted(rec2["swapped"]) == ["replica0", "replica1"]
    for r in reps:
        assert r.engine.adapter_bank.row_of("summarize-v1") is not None
    h3 = router.submit(prompt, max_new_tokens=8, adapter="summarize-v1")
    _drive_router(router, clock)
    np.testing.assert_array_equal(h3.result(timeout=0), solo)


@pytest.mark.lora
@pytest.mark.slow
@pytest.mark.fault_matrix
def test_replica_crash_mid_adapter_stream_fails_over_bit_identical(
        gpt_tiny):
    """A replica hard-crashed MID-adapter-stream: the adapter id rides
    the RouterHandle, the survivor (same adapter registered) re-prefills
    through the SAME bank row, and the stream finishes bit-identical to
    an uninterrupted solo adapter decode."""
    from paddle_tpu import serving
    from paddle_tpu.utils.fault_injection import FaultPlan, set_global_plan

    clock = serving.SimClock()
    tree = _mk_tree(gpt_tiny, 1)
    prompt = np.random.RandomState(9).randint(
        1, 500, size=(6,)).astype(np.int32)
    solo = _solo_adapter_decode(gpt_tiny, clock, tree, prompt, 12)

    reps = [serving.InProcessReplica(_armed(gpt_tiny, clock), i)
            for i in range(2)]
    router = serving.ReplicaRouter(reps)
    for r in reps:
        r.engine.register_adapter("ad1", tree)

    handles = [router.submit(prompt, max_new_tokens=12, adapter="ad1")
               for _ in range(2)]          # load-aware: one per replica
    assert {h._replica.name for h in handles} == {"replica0", "replica1"}
    for _ in range(5):
        clock.advance(0.01)
        router.pump()
    assert all(len(h.tokens_so_far()) > 0 for h in handles)

    set_global_plan(FaultPlan.from_spec("replica_crash@0"))
    _drive_router(router, clock)
    victims = [h for h in handles if h.failovers == 1]
    assert len(victims) == 1
    for h in handles:
        np.testing.assert_array_equal(h.result(timeout=0), solo)
    snap = router.metrics.snapshot()
    assert snap["completed"] == 2 and snap["failed"] == 0


# ---- certified adapter weight sets + fleet rollout ----

def test_adapter_weightset_certify_for_typed_refusals(gpt_tiny, tmp_path):
    """AdapterWeightSet: own format string, mandatory signature block,
    `certify_for` passes on the matching base model and refuses typed
    (`adapter_mismatch`) on rank / target skew; a plain WeightSet never
    certifies as an adapter set."""
    from paddle_tpu.checkpoint import (AdapterWeightSet,
                                       UncertifiedWeightsError, WeightSet)
    from paddle_tpu.tuning import adapter_signature

    tree = _mk_tree(gpt_tiny, 1)
    sig = adapter_signature(gpt_tiny, 4)
    ws = AdapterWeightSet.publish(str(tmp_path), "ad-v1", tree, sig)
    manifest = ws.certify_for(sig)
    assert manifest["format"] == "pdtpu.adapter.v1"
    assert manifest["adapter"]["rank"] == 4

    wrong = dict(sig, rank=8)
    with pytest.raises(UncertifiedWeightsError) as exc:
        ws.certify_for(wrong)
    assert exc.value.reason == "adapter_mismatch"
    assert "rank" in str(exc.value)

    # a base-format WeightSet of the same bytes is NOT an adapter set
    with pytest.raises(UncertifiedWeightsError) as exc:
        WeightSet(str(tmp_path), "ad-v1").certify()
    assert exc.value.reason == "bad_format"

    with pytest.raises(ValueError):
        AdapterWeightSet.publish(str(tmp_path), "ad-v2", tree, None)


# ---- economics + observability ----

def test_ledger_adapter_owner_rebucketing():
    """`adapter_owners` re-buckets the SAME per-row shares by adapter
    id: per-adapter device seconds sum exactly to the tenant totals of
    the same dispatches, tokens likewise."""
    from paddle_tpu.obs.serving_ledger import ServingLedger

    led = ServingLedger()
    with led.measure("host"):
        led.book_dispatch(
            0.10, 4, 6, 16,
            owners=[("acme", "interactive", 6), ("beta", "batch", 4)],
            adapter_owners=[("ad1", 6), ("base", 4)])
        led.book_dispatch(
            0.05, 0, 10, 16,
            owners=[("acme", "interactive", 10)],
            adapter_owners=[("ad1", 4), ("ad2", 6)])
    snap = led.snapshot()
    tenants_s = sum(v["device_seconds"] for v in snap["tenants"].values())
    adapters_s = sum(v["device_seconds"]
                     for v in snap["adapters"].values())
    assert abs(tenants_s - adapters_s) < 1e-12
    assert abs(tenants_s - 0.15) < 1e-12
    assert snap["adapters"]["ad1"]["tokens"] == 10
    assert snap["adapters"]["ad2"]["tokens"] == 6
    assert snap["adapters"]["base"]["tokens"] == 4
    assert sum(v["tokens"] for v in snap["adapters"].values()) == \
        sum(v["tokens"] for v in snap["tenants"].values())


@pytest.mark.lora
def test_metrics_adapter_token_families_render(gpt_tiny):
    """pdtpu_llm_adapter_* families: per-adapter token counters (base
    rows bucketed as adapter="base") and swap/rollback counters render
    on the same scrape as the engine families."""
    from paddle_tpu import serving
    clock = serving.SimClock()
    eng = _armed(gpt_tiny, clock)
    snap0 = eng.register_adapter("ad1", _mk_tree(gpt_tiny, 1))
    prompt = np.arange(1, 6, dtype=np.int32)
    hs = [eng.submit(prompt, max_new_tokens=3, adapter="ad1"),
          eng.submit(prompt, max_new_tokens=3)]
    _drive(eng, clock)
    for h in hs:
        h.result(timeout=0)
    eng.rollback_adapter("ad1", snap0)     # snap0 None → unload
    snap = eng.metrics.snapshot()
    assert snap["adapter_tokens"]["ad1"] == 3
    assert snap["adapter_tokens"]["base"] == 3
    text = eng.metrics.render()
    assert 'pdtpu_llm_adapter_tokens_total{adapter="ad1"} 3' in text
    assert 'pdtpu_llm_adapter_swaps_total 1' in text
    assert 'pdtpu_llm_adapter_rollbacks_total 1' in text
    eng.stop()


def test_lora_decode_flops_helper():
    """Σ 2·r·(in+out) over every adapted site, stdlib arithmetic."""
    from paddle_tpu.obs.flops import lora_decode_flops_per_token
    assert lora_decode_flops_per_token(8, [(4, 4), (4, 8)]) == \
        2 * 8 * 8 + 2 * 8 * 12
    assert lora_decode_flops_per_token(1, []) == 0.0
