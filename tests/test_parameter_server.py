"""Minimal functional PS runtime (VERDICT r2 item 9; reference
fleet/runtime/the_one_ps.py:286, brpc_ps_{client,server},
common_sparse_table.cc, distributed_lookup_table op)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.runtime import (PSClient, PSEmbedding,
                                                  PSServer, SparseTable,
                                                  TheOnePSRuntime)
from paddle_tpu.distributed.fleet.runtime.the_one_ps import (PSCore,
                                                             SparseAccessor)


@pytest.fixture(autouse=True)
def _teardown():
    yield
    fleet.stop_worker()
    fleet.fleet()._ps_runtime = None


def test_sparse_table_demand_rows_and_sgd():
    t = SparseTable(4, SparseAccessor("sgd", lr=0.5), init_std=0.0)
    vals = t.pull(np.array([3, 7]))
    np.testing.assert_allclose(vals, 0.0)  # init_std=0 -> zero rows
    t.push(np.array([3, 3, 7]),
           np.array([[1.0] * 4, [1.0] * 4, [2.0] * 4], np.float32))
    vals = t.pull(np.array([3, 7]))
    # duplicate ids merge before the rule: row3 -= 0.5*2, row7 -= 0.5*2
    np.testing.assert_allclose(vals[0], -1.0)
    np.testing.assert_allclose(vals[1], -1.0)


def test_client_shards_rows_across_cores():
    cores = [PSCore(), PSCore()]
    client = PSClient(cores=cores)
    client.create_table("emb", 4, lr=0.1, init_std=0.01)
    ids = np.arange(10)
    vals = client.pull_sparse("emb", ids)
    assert vals.shape == (10, 4)
    # rows land on core id%2
    assert set(cores[0].tables["emb"]._rows) == {0, 2, 4, 6, 8}
    assert set(cores[1].tables["emb"]._rows) == {1, 3, 5, 7, 9}
    client.push_sparse("emb", ids, np.ones((10, 4), np.float32))
    vals2 = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(vals2, vals - 0.1, atol=1e-6)


def test_http_transport_roundtrip():
    """The brpc stand-in: pull/push over the HTTP RPC pair."""
    core = PSCore()
    server = PSServer(core).start()
    try:
        client = PSClient(endpoints=[f"127.0.0.1:{server.port}"])
        client.create_table("emb", 8, rule="adagrad", lr=0.1)
        vals = client.pull_sparse("emb", np.array([5, 9]))
        assert vals.shape == (2, 8)
        client.push_sparse("emb", np.array([5]),
                           np.ones((1, 8), np.float32))
        vals2 = client.pull_sparse("emb", np.array([5]))
        assert not np.allclose(vals2, vals[0])
    finally:
        server.stop()


def test_recommendation_fixture_trains():
    """Sparse-embedding recommendation model: PS tables for user/item ids,
    local dense tower, loss decreases (dist_fleet fixture analog)."""
    rt = fleet.init_server(n_shards=2)
    fleet.run_server()
    client = fleet.init_worker()

    paddle.seed(0)
    user_emb = PSEmbedding(client, "user", 1000, 8, lr=0.2, init_std=0.1)
    item_emb = PSEmbedding(client, "item", 1000, 8, lr=0.2, init_std=0.1)
    tower = paddle.nn.Linear(16, 1)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=tower.parameters())
    rng = np.random.RandomState(0)
    users = rng.randint(0, 1000, (64,))
    items = rng.randint(0, 1000, (64,))
    labels = paddle.to_tensor(
        rng.randint(0, 2, (64, 1)).astype(np.float32))
    bce = paddle.nn.BCEWithLogitsLoss()

    rows_before = client.pull_sparse("user", np.unique(users))
    losses = []
    for _ in range(25):
        u = user_emb(paddle.to_tensor(users))
        it = item_emb(paddle.to_tensor(items))
        logits = tower(paddle.concat([u, it], axis=-1))
        loss = bce(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] - 0.05, losses
    # the sparse rows trained SERVER-side (accessor rule), not locally
    rows_after = client.pull_sparse("user", np.unique(users))
    assert not np.allclose(rows_before, rows_after)


def test_ps_save_load_roundtrip(tmp_path):
    rt = fleet.init_server(n_shards=2)
    client = fleet.init_worker()
    client.create_table("emb", 4, lr=0.1, init_std=0.1)
    before = client.pull_sparse("emb", np.arange(6))
    fleet.save_persistables(dirname=str(tmp_path))
    fleet.stop_worker()
    fleet.fleet()._ps_runtime = None

    rt2 = fleet.init_server(dirname=str(tmp_path), n_shards=2)
    client2 = fleet.init_worker()
    after = client2.pull_sparse("emb", np.arange(6))
    np.testing.assert_allclose(after, before)


def test_init_worker_without_server_raises():
    with pytest.raises(RuntimeError, match="init_server"):
        fleet.init_worker()


def test_ps_load_reshards_to_different_shard_count(tmp_path):
    """Restoring with a different n_shards must re-distribute rows, not
    silently lose the odd-id half (review finding)."""
    fleet.init_server(n_shards=2)
    client = fleet.init_worker()
    client.create_table("emb", 4, rule="adagrad", lr=0.5, init_std=0.1)
    before = client.pull_sparse("emb", np.arange(9))
    fleet.save_persistables(dirname=str(tmp_path))
    fleet.stop_worker()
    fleet.fleet()._ps_runtime = None

    fleet.init_server(dirname=str(tmp_path), n_shards=3)
    client2 = fleet.init_worker()
    after = client2.pull_sparse("emb", np.arange(9))
    np.testing.assert_allclose(after, before)
    # the accessor config came back too
    t = fleet.fleet()._ps_runtime.cores[0].tables["emb"]
    assert t.accessor.rule == "adagrad" and t.accessor.lr == 0.5


def test_fleet_wrapper_legacy_api(tmp_path):
    """FleetWrapper (framework/fleet/fleet_wrapper.h legacy PS singleton)
    rides the PS runtime."""
    from paddle_tpu.distributed.fleet.utils.fleet_wrapper import FleetWrapper
    fleet.init_server(n_shards=2)
    fleet.run_server()
    fw = FleetWrapper()
    assert fw is FleetWrapper()  # singleton
    fw.create_table(7, 4, rule="sgd", lr=0.5, init_std=0.0)
    vals = fw.pull_sparse(7, np.array([1, 2]))
    np.testing.assert_allclose(vals, 0.0)
    fw.push_sparse(7, np.array([1]), np.ones((1, 4), np.float32))
    np.testing.assert_allclose(fw.pull_sparse(7, np.array([1])), -0.5)
    fw.save_model(str(tmp_path))
    fw.stop_server()


def test_distributed_lookup_table_op():
    """pscore distributed_lookup_table op contract: pull on forward, sparse
    push on backward, default client from the fleet runtime."""
    from paddle_tpu.distributed.fleet.runtime import (
        distributed_lookup_table)
    fleet.init_server(n_shards=2)
    fleet.run_server()
    client = fleet.init_worker()
    client.create_table("lt", 4, rule="sgd", lr=1.0, init_std=0.0)
    ids = paddle.to_tensor(np.array([[1, 2], [2, 3]], np.int64))
    out = distributed_lookup_table(ids, "lt")  # client resolved from fleet
    assert tuple(out.shape) == (2, 2, 4)
    out.sum().backward()  # hook pushes grads: rows 1,3 grad 1; row 2 grad 2
    after = client.pull_sparse("lt", np.array([1, 2, 3]))
    np.testing.assert_allclose(after[0], -1.0)
    np.testing.assert_allclose(after[1], -2.0)
    np.testing.assert_allclose(after[2], -1.0)


# ---- round-4: dense tables + async communicator (VERDICT r3 item 6;
# reference communicator.h, common_dense_table.cc) ----

def test_dense_table_push_pull_and_save_load(tmp_path):
    rt = fleet.init_server(n_shards=3)
    client = fleet.init_worker()
    client.create_dense_table("fc_w", (4, 2), rule="adagrad", lr=0.5)
    v0 = client.pull_dense("fc_w")
    np.testing.assert_allclose(v0, 0.0)
    g = np.ones((4, 2), np.float32)
    client.push_dense("fc_w", g)
    client.push_dense("fc_w", g)
    v1 = client.pull_dense("fc_w")
    assert not np.allclose(v1, v0)
    rt.save(str(tmp_path / "ck"))
    fleet.stop_worker()
    fleet.fleet()._ps_runtime = None

    rt2 = fleet.init_server(dirname=str(tmp_path / "ck"), n_shards=2)
    client2 = fleet.init_worker()
    np.testing.assert_allclose(client2.pull_dense("fc_w"), v1)
    # AdaGrad slot restored: the next identical push moves the values by
    # exactly the same amount a continuous run would
    client2.push_dense("fc_w", g)
    rt3 = fleet.init_server(n_shards=3)  # continuous reference
    c3 = fleet.init_worker()
    c3.create_dense_table("fc_w", (4, 2), rule="adagrad", lr=0.5)
    for _ in range(3):
        c3.push_dense("fc_w", g)
    np.testing.assert_allclose(client2.pull_dense("fc_w"),
                               c3.pull_dense("fc_w"), rtol=1e-6)


def test_communicator_sync_and_async_share_tables():
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        Communicator, TheOnePSRuntime)
    ids = np.array([1, 2], np.int64)
    g = np.ones((2, 4), np.float32)

    def run(mode):
        rt = TheOnePSRuntime(n_shards=2)
        rt.client.create_table("emb", 4, lr=0.5, init_std=0.0)
        rt.client.pull_sparse("emb", ids)
        comm = Communicator(rt.client, mode=mode,
                            max_merge_var_num=4).start()
        for _ in range(5):
            comm.push_sparse("emb", ids, g)
        comm.stop()
        return rt.client.pull_sparse("emb", ids)

    np.testing.assert_allclose(run("sync"), run("async"), rtol=1e-6)
    # 5 pushes of -0.5 each → rows at -2.5
    np.testing.assert_allclose(run("sync"), -2.5)


def test_communicator_merge_before_push():
    """max_merge_var_num batches consecutive same-table pushes into ONE
    client RPC (merge-before-push)."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        Communicator, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.client.create_table("emb", 2, lr=1.0, init_std=0.0)
    rt.client.pull_sparse("emb", np.array([0]))
    calls = []
    orig = rt.client.push_sparse
    rt.client.push_sparse = lambda t, i, g: (
        calls.append(len(i)) or orig(t, i, g))
    comm = Communicator(rt.client, mode="async", max_merge_var_num=8)
    for _ in range(6):
        comm.push_sparse("emb", np.array([0], np.int64),
                         np.ones((1, 2), np.float32))
    comm.start()
    comm.stop()
    assert sum(calls) == 6
    assert len(calls) < 6, f"no merging happened: {calls}"
    # merged server-side result identical to 6 single pushes
    np.testing.assert_allclose(
        rt.client.pull_sparse("emb", np.array([0]))[0], -6.0)


def test_communicator_staleness_bound_blocks():
    """The bounded send queue is the geo staleness guarantee: a worker
    cannot run more than k un-sent batches ahead."""
    import threading as th
    import time
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        Communicator, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.client.create_table("emb", 2, lr=1.0, init_std=0.0)
    rt.client.pull_sparse("emb", np.array([0]))
    comm = Communicator(rt.client, mode="async", send_queue_size=2)
    # not started: queue fills to the bound
    comm.push_sparse("emb", np.array([0], np.int64),
                     np.ones((1, 2), np.float32))
    comm.push_sparse("emb", np.array([0], np.int64),
                     np.ones((1, 2), np.float32))
    done = th.Event()

    def third_push():
        comm.push_sparse("emb", np.array([0], np.int64),
                         np.ones((1, 2), np.float32))
        done.set()

    t = th.Thread(target=third_push, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not done.is_set(), "push did not block at the staleness bound"
    comm.start()  # sender drains; the blocked push completes
    assert done.wait(5), "blocked push never completed after drain"
    comm.stop()
    np.testing.assert_allclose(
        rt.client.pull_sparse("emb", np.array([0]))[0], -3.0)


def test_fleet_a_sync_worker_trains_async():
    """strategy.a_sync wires fleet.init_worker to the Communicator-backed
    client; the recommendation fixture still converges (async-PS mode)."""
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import AsyncPSClient
    strategy = DistributedStrategy()
    strategy.a_sync = True
    strategy.a_sync_configs.k_steps = 4  # geo staleness bound
    strategy.a_sync_configs.max_merge_var_num = 2
    fleet.init(is_collective=True, strategy=strategy)
    try:
        fleet.init_server(n_shards=2)
        fleet.run_server()
        client = fleet.init_worker()
        assert isinstance(client, AsyncPSClient)

        paddle.seed(0)
        emb = PSEmbedding(client, "user", 500, 8, lr=0.2, init_std=0.1)
        tower = paddle.nn.Linear(8, 1)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=tower.parameters())
        rng = np.random.RandomState(0)
        users = rng.randint(0, 500, (64,))
        labels = paddle.to_tensor(
            rng.randint(0, 2, (64, 1)).astype(np.float32))
        bce = paddle.nn.BCEWithLogitsLoss()
        losses = []
        for _ in range(30):
            u = emb(paddle.to_tensor(users))
            loss = bce(tower(u), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.item()))
        client.flush()  # barrier: all queued grads applied server-side
        assert losses[-1] < losses[0] - 0.03, losses
    finally:
        fleet.stop_worker()
        fleet.fleet()._strategy = None


# ---- heterogeneous-PS analog: worker hot-row cache tier ----

def test_heter_cache_serves_hot_rows_and_counts():
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        HeterPSCache, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=2)
    rt.client.create_table("emb", 4, lr=0.5, init_std=0.1)
    cache = HeterPSCache(rt.client, capacity=10, max_staleness=1)
    ids = np.array([1, 2, 3], np.int64)
    v1 = cache.pull_sparse("emb", ids)
    assert cache.misses == 3 and cache.hits == 0
    v2 = cache.pull_sparse("emb", ids)  # all hot now
    assert cache.hits == 3
    np.testing.assert_allclose(v2, v1)
    assert cache.hit_rate == 0.5
    # duplicate ids reassemble through the unique/inverse path
    v3 = cache.pull_sparse("emb", np.array([2, 2, 1], np.int64))
    np.testing.assert_allclose(v3[0], v3[1])
    np.testing.assert_allclose(v3[2], v1[0])


def test_heter_cache_push_invalidates_and_ages():
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        HeterPSCache, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.client.create_table("emb", 2, lr=1.0, init_std=0.0)
    cache = HeterPSCache(rt.client, max_staleness=1)
    ids = np.array([5], np.int64)
    cache.pull_sparse("emb", ids)
    # push through the cache: server row moves AND the cached copy dies
    cache.push_sparse("emb", ids, np.ones((1, 2), np.float32))
    after = cache.pull_sparse("emb", ids)
    np.testing.assert_allclose(after, -1.0)  # fresh from the server
    # a different row cached now ages out after max_staleness pushes
    cache.pull_sparse("emb", np.array([7], np.int64))
    cache.push_sparse("emb", ids, np.ones((1, 2), np.float32))  # tick 1
    pre = cache.hits
    cache.pull_sparse("emb", np.array([7], np.int64))  # still fresh
    assert cache.hits == pre + 1
    cache.push_sparse("emb", ids, np.ones((1, 2), np.float32))  # tick 2
    pre_m = cache.misses
    cache.pull_sparse("emb", np.array([7], np.int64))  # staleness exceeded
    assert cache.misses == pre_m + 1


def test_heter_cache_lru_eviction():
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        HeterPSCache, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.client.create_table("emb", 2, lr=1.0, init_std=0.1)
    cache = HeterPSCache(rt.client, capacity=2)
    cache.pull_sparse("emb", np.array([1], np.int64))
    cache.pull_sparse("emb", np.array([2], np.int64))
    cache.pull_sparse("emb", np.array([1], np.int64))  # touch 1 (hot)
    cache.pull_sparse("emb", np.array([3], np.int64))  # evicts 2 (coldest)
    pre_h, pre_m = cache.hits, cache.misses
    cache.pull_sparse("emb", np.array([1], np.int64))
    assert cache.hits == pre_h + 1
    cache.pull_sparse("emb", np.array([2], np.int64))
    assert cache.misses == pre_m + 1


def test_fleet_heter_ccl_mode_wraps_worker_in_cache():
    from paddle_tpu.distributed import DistributedStrategy
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import HeterPSCache
    strategy = DistributedStrategy()
    strategy.heter_ccl_mode = True
    fleet.init(is_collective=True, strategy=strategy)
    try:
        fleet.init_server(n_shards=2)
        fleet.run_server()
        client = fleet.init_worker()
        assert isinstance(client, HeterPSCache)
        # end to end: the PSEmbedding trains through the cache tier
        paddle.seed(0)
        emb = PSEmbedding(client, "u", 100, 4, lr=0.2, init_std=0.1)
        ids = paddle.to_tensor(np.array([3, 4, 3], np.int64))
        out = emb(ids)
        out.sum().backward()
        assert cache_hit_total(client) > 0  # the PSEmbedding path went through the cache
        v = client.pull_sparse("u", np.array([3], np.int64))
        assert np.isfinite(v).all()
    finally:
        fleet.stop_worker()
        fleet.fleet()._strategy = None


def cache_hit_total(c):
    return c.hits + c.misses


def test_heter_cache_empty_ids_and_load_invalidation(tmp_path):
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        HeterPSCache, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.client.create_table("emb", 2, lr=1.0, init_std=0.1)
    cache = HeterPSCache(rt.client)
    rt.register_worker_cache(cache)
    assert cache.pull_sparse("emb", np.array([], np.int64)).shape == (0, 0)
    v0 = cache.pull_sparse("emb", np.array([1], np.int64))
    rt.save(str(tmp_path / "ck"))
    # mutate server-side, then load the checkpoint: the cache must refetch
    rt.client.push_sparse("emb", np.array([1], np.int64),
                          np.ones((1, 2), np.float32))
    rt.load(str(tmp_path / "ck"))
    v1 = cache.pull_sparse("emb", np.array([1], np.int64))
    np.testing.assert_allclose(v1, v0)  # restored rows, not cached stale


def test_heter_init_worker_idempotent():
    from paddle_tpu.distributed import DistributedStrategy
    strategy = DistributedStrategy()
    strategy.heter_ccl_mode = True
    strategy.a_sync = True
    fleet.init(is_collective=True, strategy=strategy)
    try:
        fleet.init_server(n_shards=1)
        fleet.run_server()
        c1 = fleet.init_worker()
        c2 = fleet.init_worker()
        assert c1 is c2  # no duplicate Communicator/cache
    finally:
        fleet.stop_worker()
        fleet.fleet()._strategy = None


# ---------------- native C++ transport (csrc/pstransport) ----------------

def _native_pair(n=2):
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServer)
    servers = [NativePSServer() for _ in range(n)]
    client = NativePSClient([s.endpoint for s in servers])
    return servers, client


def test_native_transport_sparse_roundtrip():
    """brpc-class C++ transport: server-resident tables, server-side rule."""
    servers, client = _native_pair(2)
    try:
        client.create_table("emb", 8, rule="sgd", lr=0.5, init_std=0.0)
        ids = np.array([3, 4, 7, 3])
        vals = client.pull_sparse("emb", ids)
        assert vals.shape == (4, 8)
        np.testing.assert_allclose(vals, 0.0)
        client.push_sparse("emb", ids, np.ones((4, 8), np.float32))
        # duplicate id 3 merges: grad 2, sgd step -0.5*2
        out = client.pull_sparse("emb", np.array([3, 4]))
        np.testing.assert_allclose(out[0], -1.0, atol=1e-6)
        np.testing.assert_allclose(out[1], -0.5, atol=1e-6)
        assert client.table_size("emb") == 3
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_native_transport_adagrad_and_save_load(tmp_path):
    servers, client = _native_pair(1)
    try:
        client.create_table("e", 4, rule="adagrad", lr=1.0, init_std=0.0)
        ids = np.array([5])
        g = np.full((1, 4), 3.0, np.float32)
        client.pull_sparse("e", ids)
        client.push_sparse("e", ids, g)
        v1 = client.pull_sparse("e", ids)
        np.testing.assert_allclose(v1, -1.0, atol=1e-4)  # 3/sqrt(9)
        client.save(str(tmp_path / "ckpt"))
        client.push_sparse("e", ids, g)  # diverge
        client.load(str(tmp_path / "ckpt"))
        v2 = client.pull_sparse("e", ids)
        np.testing.assert_allclose(v2, v1, atol=1e-6)
        # slot restored too: next step uses sqrt(18), not sqrt(9)
        client.push_sparse("e", ids, g)
        v3 = client.pull_sparse("e", ids)
        np.testing.assert_allclose(v3, v1 - 3.0 / np.sqrt(18.0), atol=1e-4)
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_native_transport_dense_table():
    servers, client = _native_pair(2)
    try:
        client.create_dense_table("fc.w", (2, 3), rule="sgd", lr=0.1)
        v = client.pull_dense("fc.w")
        assert v.shape == (2, 3)
        client.push_dense("fc.w", np.ones((2, 3), np.float32))
        np.testing.assert_allclose(client.pull_dense("fc.w"), -0.1,
                                   atol=1e-6)
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_native_transport_runtime_integration():
    """TheOnePSRuntime swaps transports without touching callers."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=2).run_server(transport="native")
    try:
        rt.client.create_table("emb", 4, rule="sgd", lr=0.1, init_std=0.0)
        ids = np.arange(10)
        rt.client.pull_sparse("emb", ids)
        rt.client.push_sparse("emb", ids, np.ones((10, 4), np.float32))
        out = rt.client.pull_sparse("emb", ids)
        np.testing.assert_allclose(out, -0.1, atol=1e-6)
    finally:
        rt.client.close()
        for s in rt.servers:
            s.stop()


def test_barrier_table_releases_all_waiters():
    """barrier_table.cc analog: all trainers block until the last arrives."""
    import threading as th
    import time
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import PSCore
    core = PSCore()
    bt = core.create_barrier_table("epoch", trigger=3)
    released = []

    def worker(i):
        assert bt.barrier(i, timeout=10.0)
        released.append(i)

    threads = [th.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert released == []  # 2 of 3 arrived: still fenced
    worker(2)  # last trainer releases everyone
    for t in threads:
        t.join(5)
    assert sorted(released) == [0, 1, 2]
    # next round works (state reset)
    t2 = [th.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in t2:
        t.start()
    for t in t2:
        t.join(5)
    assert len(released) == 6


def test_native_load_clears_post_save_rows(tmp_path):
    """A restore is a restore: rows materialized after the save must not
    survive load, and an empty/foreign checkpoint dir raises instead of
    silently serving fresh random rows."""
    servers, client = _native_pair(1)
    try:
        client.create_table("e", 4, rule="sgd", lr=0.1, init_std=0.0)
        client.pull_sparse("e", np.array([1, 2]))
        client.save(str(tmp_path / "ck"))
        client.pull_sparse("e", np.array([3]))  # post-save row
        assert client.table_size("e") == 3
        client.load(str(tmp_path / "ck"))
        assert client.table_size("e") == 2
        import pytest as _pytest
        with _pytest.raises(FileNotFoundError):
            client.load(str(tmp_path / "nope"))
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_native_load_truncated_file_preserves_table(tmp_path):
    """A corrupt/truncated checkpoint must fail the load AND leave the live
    table untouched (load parses into temporaries, swaps on success)."""
    servers, client = _native_pair(1)
    try:
        client.create_table("e", 4, rule="sgd", lr=0.1, init_std=0.0)
        client.pull_sparse("e", np.array([1, 2]))
        client.push_sparse("e", np.array([1]), np.ones((1, 4), np.float32))
        before = client.pull_sparse("e", np.array([1, 2]))
        client.save(str(tmp_path / "ck"))
        path = tmp_path / "ck" / "shard0" / "e.pstab"
        raw = path.read_bytes()
        path.write_bytes(raw[:len(raw) - 7])  # truncate mid-row
        import pytest as _pytest
        with _pytest.raises(RuntimeError):
            client.load(str(tmp_path / "ck"))
        after = client.pull_sparse("e", np.array([1, 2]))
        np.testing.assert_allclose(after, before, atol=1e-7)
        assert client.table_size("e") == 2
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_count_filter_entry_admission_survives_save_load(tmp_path):
    """CountFilterEntry progress persists like optimizer slots: a restore
    must not reset the admission counters."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        CountFilterEntry, SparseAccessor, SparseTable, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    t = rt.cores[0].create_table("e", 4, entry=CountFilterEntry(3))
    t.pull(np.array([5]))
    t.pull(np.array([5]))          # 2 of 3 sightings
    assert len(t._rows) == 0
    rt.save(str(tmp_path / "ck"))
    rt2 = TheOnePSRuntime(n_shards=1)
    rt2.cores[0].create_table("e", 4, entry=CountFilterEntry(3))
    rt2.load(str(tmp_path / "ck"))
    t2 = rt2.cores[0].tables["e"]
    t2.pull(np.array([5]))         # third sighting: admitted
    assert len(t2._rows) == 1


def test_entry_policy_restored_from_checkpoint(tmp_path):
    """The admission policy itself round-trips: a fresh runtime that loads
    the checkpoint re-arms CountFilterEntry without manual re-creation."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        CountFilterEntry, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=1)
    rt.cores[0].create_table("e", 4, entry=CountFilterEntry(3))
    rt.save(str(tmp_path / "ck"))
    rt2 = TheOnePSRuntime(n_shards=1)
    rt2.load(str(tmp_path / "ck"))
    t2 = rt2.cores[0].tables["e"]
    assert isinstance(t2.entry, CountFilterEntry) and t2.entry.count == 3
    t2.pull(np.array([9]))
    assert len(t2._rows) == 0  # still gated after restore


def test_unadmitted_duplicate_ids_consistent_in_one_pull():
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        CountFilterEntry, SparseAccessor, SparseTable)
    t = SparseTable(4, SparseAccessor(), init_std=0.5,
                    entry=CountFilterEntry(10))
    out = t.pull(np.array([5, 5, 5]))
    np.testing.assert_allclose(out[0], out[1])
    np.testing.assert_allclose(out[1], out[2])


# ---------------- transport hardening (VERDICT r4 item 7) ----------------

def test_native_transport_ping_and_heartbeat():
    """service/env.h heartbeat analog: ping answers on live shards, the
    background heartbeat marks a killed shard dead."""
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServerProcess)
    import time
    servers = [NativePSServerProcess() for _ in range(2)]
    client = NativePSClient([s.endpoint for s in servers], timeout_ms=2000,
                            retries=1, retry_backoff=0.05)
    try:
        assert client.alive() == [True, True]
        client.start_heartbeat(interval_s=0.2)
        servers[1].kill()
        deadline = time.time() + 10
        while time.time() < deadline and not client.dead[1]:
            time.sleep(0.1)
        assert client.dead[1], "heartbeat never marked the killed shard dead"
        assert not client.dead[0]
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_native_transport_rpc_timeout_not_hang():
    """A dead server must fail the rpc within the deadline, never hang the
    worker (the round-4 weakness: blocking client, dead server = hang)."""
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServerProcess)
    import time
    srv = NativePSServerProcess()
    client = NativePSClient([srv.endpoint], timeout_ms=1500, retries=1,
                            retry_backoff=0.05)
    try:
        client.create_table("e", 4, rule="sgd", lr=0.5, init_std=0.0)
        client.pull_sparse("e", np.arange(4))
        srv.kill()
        t0 = time.time()
        with pytest.raises(RuntimeError, match="shard 0.*marked\n?.*dead|"
                                               "marked"):
            client.pull_sparse("e", np.arange(4))
        assert time.time() - t0 < 15, "rpc to a dead server effectively hung"
    finally:
        client.close()
        srv.stop()


def test_native_transport_reconnect_after_transient_drop():
    """brpc retry analog: the SERVER staying up but a connection dying must
    be healed transparently by reconnect-and-retry."""
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServerProcess)
    srv = NativePSServerProcess()
    client = NativePSClient([srv.endpoint], timeout_ms=2000, retries=2,
                            retry_backoff=0.05)
    try:
        client.create_table("e", 4, rule="sgd", lr=0.5, init_std=0.0)
        client.pull_sparse("e", np.arange(4))
        # sabotage the live connection (simulates a dropped TCP session)
        client._lib.ps_disconnect(client._conns[0])
        client._conns[0] = None
        out = client.pull_sparse("e", np.arange(4))  # heals via reconnect
        assert out.shape == (4, 4)
    finally:
        client.close()
        srv.stop()


def test_native_transport_kill_shard_failover(tmp_path):
    """The VERDICT acceptance case: kill one shard mid-training, bring up a
    replacement process, repoint + restore from checkpoint, and training
    completes with shard-0 state intact and shard-1 state at the
    checkpoint."""
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServerProcess)
    servers = [NativePSServerProcess() for _ in range(2)]
    client = NativePSClient([s.endpoint for s in servers], timeout_ms=2000,
                            retries=1, retry_backoff=0.05)
    ckpt = str(tmp_path / "ckpt")
    try:
        client.create_table("emb", 4, rule="sgd", lr=0.5, init_std=0.0)
        ids = np.arange(8)  # even ids -> shard 0, odd -> shard 1
        client.pull_sparse("emb", ids)
        for _ in range(2):  # train: rows at -0.5*2 = -1.0
            client.push_sparse("emb", ids, np.ones((8, 4), np.float32))
        client.save(ckpt)

        servers[1].kill()
        assert client.alive() == [True, False]

        # replacement shard process + repoint + checkpoint restore
        servers[1] = NativePSServerProcess()
        assert client.reconnect(1, servers[1].endpoint)
        client.create_table("emb", 4, rule="sgd", lr=0.5, init_std=0.0)
        client.load(ckpt)
        assert client.alive() == [True, True]

        # training continues to completion across BOTH shards
        client.push_sparse("emb", ids, np.ones((8, 4), np.float32))
        out = client.pull_sparse("emb", ids)
        np.testing.assert_allclose(out, -1.5, atol=1e-6)
        assert client.table_size("emb") == 8
    finally:
        client.close()
        for s in servers:
            s.stop()


# ---------------- graph table (common_graph_table.cc analog) ----------------

def _graph_client(n=2):
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (PSClient,
                                                                 PSCore)
    cores = [PSCore() for _ in range(n)]
    client = PSClient(cores=cores)
    client.create_graph_table("g", seed=7)
    return client


def test_graph_table_edges_and_weighted_sampling():
    client = _graph_client(2)
    # star: node 0 -> 1..5 with weight == dst (sharded: 0 lives on core 0)
    src = np.zeros(5, np.int64)
    dst = np.arange(1, 6)
    client.graph_add_edges("g", src, dst, dst.astype(np.float32))
    client.graph_add_edges("g", [1], [0])  # odd node -> shard 1
    assert client.graph_size("g") == 2  # nodes 0 and 1 hold edges

    # full pull: all 5 neighbors with their weights
    (nbr, w), = client.graph_sample_neighbors("g", [0], 10)
    order = np.argsort(nbr)
    np.testing.assert_array_equal(nbr[order], dst)
    np.testing.assert_allclose(w[order], dst.astype(np.float32))

    # sub-sample: k distinct neighbors, weights consistent with ids
    (nbr2, w2), = client.graph_sample_neighbors("g", [0], 3)
    assert len(nbr2) == 3 and len(set(nbr2.tolist())) == 3
    np.testing.assert_allclose(w2, nbr2.astype(np.float32))

    # unknown node: empty result, not an error (reference actual_size 0)
    (nbr3, w3), = client.graph_sample_neighbors("g", [99], 3)
    assert len(nbr3) == 0 and len(w3) == 0

    # weighted sampling is biased toward heavy edges: over many draws,
    # neighbor 5 (weight 5) must appear more often than neighbor 1
    counts = {i: 0 for i in range(1, 6)}
    for _ in range(300):
        (nn, _), = client.graph_sample_neighbors("g", [0], 1)
        counts[int(nn[0])] += 1
    assert counts[5] > counts[1]


def test_graph_table_nodes_feats_scan_and_checkpoint(tmp_path):
    client = _graph_client(2)
    ids = np.arange(10)
    client.graph_add_nodes("g", ids)
    assert client.graph_size("g") == 10
    np.testing.assert_array_equal(client.graph_pull_list("g", 0, 10), ids)
    np.testing.assert_array_equal(client.graph_pull_list("g", 4, 3),
                                  [4, 5, 6])

    client.graph_set_node_feat("g", [2, 3], ["label", "deg"],
                               [["a", "5"], ["b", "7"]])
    feats = client.graph_get_node_feat("g", [3, 2, 9], ["label", "deg"])
    assert feats[0] == ["b", "7"] and feats[1] == ["a", "5"]
    assert feats[2] == ["", ""]  # present node, absent feature

    sampled = client.graph_sample_nodes("g", 6)
    assert len(sampled) == 6 and len(set(sampled.tolist())) == 6
    assert set(sampled.tolist()) <= set(ids.tolist())

    # checkpoint through PSCore.save + GraphTable.load roundtrip
    core0 = client._cores[0]
    core0.save(str(tmp_path))
    from paddle_tpu.distributed.fleet.runtime.graph_table import GraphTable
    g2 = GraphTable()
    g2.load(str(tmp_path / "g.graph.npz"))
    assert g2.size() == core0.graph_tables["g"].size()
    assert g2.get_node_feat([2], ["label"]) == [["a"]]


def test_graph_table_load_edge_file(tmp_path):
    client = _graph_client(2)
    p = tmp_path / "edges.txt"
    p.write_text("0\t1\t2.0\n0\t2\t1.0\n1\t0\n")
    # files load per shard in the reference; here: route lines client-side
    # by loading into a host-side table then re-adding — use the per-shard
    # loader directly on one core for the file contract
    n = client._cores[0].graph_tables["g"].load_edges(str(p),
                                                      reverse_edge=False)
    assert n == 3
    res = client._cores[0].graph_tables["g"].random_sample_neighbors([0], 5)
    nbr, w = res[0]
    assert set(nbr.tolist()) == {1, 2}


def test_graph_table_runtime_checkpoint_and_reshard(tmp_path):
    """A checkpoint containing graph tables must load (not KeyError into
    the sparse branch) and must re-shard when the core count changes."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        PSClient, PSCore, TheOnePSRuntime)
    rt = TheOnePSRuntime(n_shards=2).run_server(transport="inproc")
    c = rt.client
    c.create_graph_table("g")
    c.graph_add_edges("g", [0, 1, 2, 3], [10, 11, 12, 13],
                      [1.0, 2.0, 3.0, 4.0])
    c.graph_set_node_feat("g", [2], ["label"], [["x"]])
    c.create_table("emb", 4, lr=0.1, init_std=0.0)  # mixed checkpoint
    c.pull_sparse("emb", np.arange(4))
    rt.save(str(tmp_path / "ck"))

    # same shard count: shard-for-shard restore
    rt2 = TheOnePSRuntime(n_shards=2).run_server(transport="inproc")
    rt2.load(str(tmp_path / "ck"))
    assert rt2.client.graph_size("g") == 4
    (nbr, w), = rt2.client.graph_sample_neighbors("g", [3], 5)
    np.testing.assert_array_equal(nbr, [13])
    assert rt2.client.graph_get_node_feat("g", [2], ["label"]) == [["x"]]

    # different shard count: node-id re-shard, nothing dropped
    rt3 = TheOnePSRuntime(n_shards=3).run_server(transport="inproc")
    rt3.load(str(tmp_path / "ck"))
    assert rt3.client.graph_size("g") == 4
    (nbr3, w3), = rt3.client.graph_sample_neighbors("g", [2], 5)
    np.testing.assert_array_equal(nbr3, [12])
    np.testing.assert_allclose(w3, [3.0])
    assert rt3.client.graph_get_node_feat("g", [2], ["label"]) == [["x"]]


# ---------------- SSD spill table (ssd_sparse_table.cc analog) --------------

def test_ssd_table_spills_and_restores_rows(tmp_path):
    """Rows past the memory budget spill to disk and come back EXACTLY
    (values + optimizer slots) when re-touched — beyond-RAM embeddings."""
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import PSCore
    core = PSCore()
    t = core.create_table("big", 4, rule="adagrad", lr=1.0, init_std=0.0,
                          table_class="ssd",
                          ssd_path=str(tmp_path / "rows"),
                          mem_row_budget=8)
    # touch 24 ids in 3 waves of 8: every wave evicts the previous one
    for wave in range(3):
        ids = np.arange(wave * 8, wave * 8 + 8)
        t.pull(ids)
        t.push(ids, np.full((8, 4), float(wave + 1), np.float32))
    assert t.mem_rows() <= 8
    assert t.disk_rows() >= 16
    # wave-0 rows were spilled twice-removed; their adagrad state must
    # survive the roundtrip: value = -g/sqrt(g^2) = -1.0 after one push
    v0 = t.pull(np.arange(8))
    np.testing.assert_allclose(v0, -1.0, atol=1e-5)
    # push again: accumulator g2sum=1 came back from disk -> next step
    # uses sqrt(1+1), NOT sqrt(1)
    t.push(np.arange(8), np.ones((8, 4), np.float32))
    v1 = t.pull(np.arange(8))
    np.testing.assert_allclose(v1, -1.0 - 1.0 / np.sqrt(2.0), atol=1e-4)


def test_ssd_table_checkpoint_merges_both_tiers(tmp_path):
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import PSCore
    core = PSCore()
    t = core.create_table("big", 4, rule="sgd", lr=0.5, init_std=0.0,
                          table_class="ssd",
                          ssd_path=str(tmp_path / "rows"),
                          mem_row_budget=4)
    t.pull(np.arange(12))
    t.push(np.arange(12), np.ones((12, 4), np.float32))
    ids, vals, _, _ = t.state()
    np.testing.assert_array_equal(ids, np.arange(12))
    np.testing.assert_allclose(vals, -0.5, atol=1e-6)
    assert t.mem_rows() < 12  # state really did merge a disk tier


# --------- heter-PS training pipeline (ps_gpu_wrapper.cc analog) -----------

def test_heter_pass_device_resident_embedding_training():
    """The heter training pipeline: one pull per PASS into a device-
    resident table, jitted per-batch gather + grad accumulation on device,
    one push per pass applied by the server-side rule."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.fleet.runtime.the_one_ps import (
        HeterPSEmbeddingPass, PSClient, PSCore)
    client = PSClient(cores=[PSCore(), PSCore()])
    emb = HeterPSEmbeddingPass(client, "emb", 4, rule="sgd", lr=0.5,
                               init_std=0.0)

    pass_ids = np.arange(10)
    emb.begin_pass(pass_ids)
    assert emb.device_table.shape == (10, 4)
    np.testing.assert_allclose(np.asarray(emb.device_table), 0.0)

    @jax.jit
    def step(table, slots, target):
        def loss_fn(t):
            e = t[slots]  # device gather from the resident table
            return jnp.mean((e - target) ** 2)
        loss, d_table = jax.value_and_grad(loss_fn)(table)
        return loss, d_table

    # two batches against the SAME resident copy — no PS traffic between
    for batch in (np.array([0, 1, 2, 3]), np.array([2, 3, 8, 9])):
        slots = emb.slots_for(batch)
        loss, d_table = step(emb.device_table, jnp.asarray(slots),
                             jnp.ones((len(batch), 4), jnp.float32))
        assert np.isfinite(float(loss))
        emb.accumulate_grad(d_table)

    acc = np.asarray(emb._grad_acc)
    # ids 2,3 appeared in both batches: their accumulated grad doubles
    np.testing.assert_allclose(acc[2], acc[0] * 2, atol=1e-6)
    assert np.abs(acc[4:8]).max() == 0.0  # untouched ids: no grad

    emb.end_pass()
    # the push landed server-side: sgd lr=0.5 moved the touched rows
    rows = client.pull_sparse("emb", pass_ids)
    assert np.abs(rows[0]).max() > 0.0
    np.testing.assert_allclose(rows[4:8], 0.0)  # untouched rows unmoved
    np.testing.assert_allclose(rows[2], rows[0] * 2, atol=1e-6)

    # a fresh pass sees the UPDATED server rows
    emb.begin_pass(np.array([0, 2]))
    np.testing.assert_allclose(np.asarray(emb.device_table),
                               rows[[0, 2]], atol=1e-7)
    emb.end_pass()

    # out-of-working-set ids fail loud, like BuildGPUTask's task scope
    emb.begin_pass(np.array([1]))
    with pytest.raises(KeyError, match="begin_pass"):
        emb.slots_for(np.array([7]))


def test_native_server_bind_any_still_reachable_via_loopback():
    """bind_any=True (the multi-host deployment shape) binds 0.0.0.0 and
    remains reachable through loopback on the same host."""
    from paddle_tpu.distributed.fleet.runtime.native_ps import (
        NativePSClient, NativePSServerProcess)
    srv = NativePSServerProcess(bind_any=True)
    client = NativePSClient([srv.endpoint], timeout_ms=2000)
    try:
        client.create_table("e", 4, rule="sgd", lr=0.5, init_std=0.0)
        out = client.pull_sparse("e", np.arange(4))
        assert out.shape == (4, 4)
        assert client.alive() == [True]
    finally:
        client.close()
        srv.stop()
