"""dy2static AST conversion (VERDICT r4 item 4).

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py:1
and its 70-file test suite (test_ifelse.py, test_loop.py,
test_break_continue.py, test_logical.py ...). Each case here follows the
reference suite's pattern: run the function eagerly (python control flow) and
under to_static/tracing (converted control flow) and assert identical
numerics — including data-dependent branches, which plain tracing cannot
handle at all."""
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import convert_function


def t(x, dtype=None):
    return paddle.to_tensor(np.asarray(x, dtype))


def _traced(fn, *arrays):
    """Run fn through jax.jit on Tensor-wrapped tracers (the to_static
    execution mode) and return numpy results."""
    def pure(*arrs):
        out = fn(*[Tensor(a) for a in arrs])
        return jax.tree_util.tree_map(
            lambda o: o.data if isinstance(o, Tensor) else o, out,
            is_leaf=lambda o: isinstance(o, Tensor))
    return jax.tree_util.tree_map(
        np.asarray, jax.jit(pure)(*[jnp.asarray(a) for a in arrays]))


# ---- if/else (reference test_ifelse.py patterns) ----

def test_if_else_assignment():
    def fn(x):
        if x.mean() > 0:
            y = x + 1
        else:
            y = x - 1
        return y

    conv = convert_function(fn)
    for data in (np.ones((3,), np.float32), -np.ones((3,), np.float32)):
        eager = np.asarray(conv(t(data)).numpy())
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(eager, ref)
        got = _traced(conv, data)
        np.testing.assert_allclose(got, ref)


def test_if_no_else():
    def fn(x):
        y = x * 2
        if x.sum() > 0:
            y = y + 10
        return y

    conv = convert_function(fn)
    for data in (np.ones((2,), np.float32), -np.ones((2,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_nested_if():
    def fn(x):
        if x.sum() > 0:
            if x.sum() > 10:
                y = x * 100
            else:
                y = x * 10
        else:
            y = x * -1
        return y

    conv = convert_function(fn)
    for data in (np.full((4,), 5.0, np.float32),
                 np.full((4,), 0.5, np.float32),
                 np.full((4,), -3.0, np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_if_early_return():
    def fn(x):
        if x.sum() > 0:
            return x + 100
        return x - 100

    conv = convert_function(fn)
    for data in (np.ones((2,), np.float32), -np.ones((2,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_if_both_branches_return():
    def fn(x):
        if x.max() > 0:
            z = x * 2
            return z + 1
        else:
            return x * -3

    conv = convert_function(fn)
    for data in (np.ones((2,), np.float32), -np.ones((2,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_chained_early_returns():
    def fn(x):
        s = x.sum()
        if s > 10:
            return x * 3
        if s > 0:
            return x * 2
        return x

    conv = convert_function(fn)
    for v in (6.0, 0.5, -1.0):
        data = np.full((4,), v, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_if_multiple_vars():
    def fn(x):
        a = x
        b = x * 0
        if x.mean() > 0:
            a = a + 1
            b = a * 2
        else:
            a = a - 1
        return a + b

    conv = convert_function(fn)
    for data in (np.ones((3,), np.float32), -np.ones((3,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_if_defined_single_branch_raises_when_traced():
    def fn(x):
        if x.sum() > 0:
            y = x + 1
        return y  # noqa: F821 — defined in one branch only

    conv = convert_function(fn)
    # eager positive path works (python semantics)
    np.testing.assert_allclose(
        np.asarray(conv(t(np.ones(2, np.float32))).numpy()),
        np.ones(2, np.float32) + 1)
    with pytest.raises(ValueError, match="only one branch"):
        _traced(conv, np.ones(2, np.float32))


def test_elif_chain():
    def fn(x):
        s = x.sum()
        if s > 10:
            y = x * 3
        elif s > 0:
            y = x * 2
        else:
            y = x * -1
        return y

    conv = convert_function(fn)
    for v in (6.0, 0.5, -2.0):
        data = np.full((3,), v, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


# ---- while (reference test_loop.py patterns) ----

def test_while_tensor_cond():
    def fn(x):
        while x.sum() < 10:
            x = x * 2
        return x

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(_traced(conv, data), ref)
    # the traced while must be a lax.while_loop, not an unrolled trace:
    # iteration count depends on data, so a second call with different data
    # through the SAME jit cache must be right
    def pure(a):
        out = conv(Tensor(a))
        return out.data
    jitted = jax.jit(pure)
    for scale in (1.0, 3.0):
        d = np.full((2,), scale, np.float32)
        np.testing.assert_allclose(np.asarray(jitted(jnp.asarray(d))),
                                   np.asarray(fn(t(d)).numpy()))


def test_while_counter_python_int():
    def fn(x, n):
        i = 0
        while i < n:
            x = x + 1
            i = i + 1
        return x

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    # tensor n -> traced loop
    ref = np.asarray(fn(t(data), t(np.int32(5))).numpy())
    np.testing.assert_allclose(ref, np.full((2,), 5.0, np.float32))
    got = _traced(conv, data, np.int32(5))
    np.testing.assert_allclose(got, ref)


def test_while_multiple_carries():
    def fn(x):
        s = x * 0
        i = x.sum() * 0
        while i < 4:
            s = s + x
            i = i + 1
        return s, i

    conv = convert_function(fn)
    data = np.full((3,), 2.0, np.float32)
    ref_s, ref_i = fn(t(data))
    got_s, got_i = _traced(conv, data)
    np.testing.assert_allclose(got_s, np.asarray(ref_s.numpy()))
    np.testing.assert_allclose(got_i, np.asarray(ref_i.numpy()))


def test_while_promotes_int_accumulator():
    """`s = 0` before `while: s = s + x(float)` must carry float32, not
    truncate to int each iteration (python promotes; so must the trace)."""
    def fn(x):
        s = 0
        i = 0
        while i < 3:
            s = s + x.mean()
            i = i + 1
        return s

    conv = convert_function(fn)
    data = np.full((2,), 0.5, np.float32)
    ref = float(np.asarray(fn(t(data)).numpy()))  # 1.5
    assert ref == pytest.approx(1.5)
    got = _traced(conv, data)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-6)


def test_if_numpy_array_branch_value_merges():
    def fn(x):
        if x.sum() > 0:
            w = np.ones(2, np.float32)
        else:
            w = np.zeros(2, np.float32)
        return x * w

    conv = convert_function(fn)
    for data in (np.ones((2,), np.float32), -np.ones((2,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_partial_passthrough_not_aliased():
    import functools

    def f(a, x):
        return x + a

    def g(b, x):
        return x * b

    pf = convert_function(functools.partial(f, 1))
    pg = convert_function(functools.partial(g, 3))
    d = t(np.full((2,), 2.0, np.float32))
    np.testing.assert_allclose(np.asarray(pf(d).numpy()), [3.0, 3.0])
    np.testing.assert_allclose(np.asarray(pg(d).numpy()), [6.0, 6.0])


def test_while_body_local_temporary_not_carried():
    """A name first assigned inside the loop body (write-before-read each
    iteration) is a body-local temporary: it must not block the traced
    while, and the loop must still match python numerics."""
    def fn(x):
        while x.sum() < 10:
            y = x + 1
            x = y
        return x

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_while_temporary_read_after_loop_fails_loud():
    def fn(x):
        while x.sum() < 10:
            y = x + 1
            x = y
        return y  # noqa: F821 — defined only on iterating paths

    conv = convert_function(fn)
    # traced: y resets to UNDEF after the loop; using it fails loud
    # (NameError/ValueError from UNDEF ops, or jax's TypeError naming the
    # _Undefined sentinel when returned directly) — never a silent value
    # or a leaked-tracer crash
    with pytest.raises((NameError, ValueError, TypeError)):
        _traced(conv, np.ones((2,), np.float32))


def test_while_with_break_stays_python():
    def fn(x):
        i = 0
        while i < 10:
            if i >= 3:
                break
            x = x + 1
            i += 1
        return x

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    np.testing.assert_allclose(np.asarray(conv(t(data)).numpy()),
                               np.full((2,), 3.0, np.float32))


# ---- for range (reference test_for_enumerate.py patterns) ----

def test_for_range_python_n():
    def fn(x):
        for i in range(3):
            x = x + i
        return x

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_for_range_tensor_stop():
    def fn(x, n):
        for _ in range(n):
            x = x * 2
        return x

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    ref = np.asarray(fn(t(data), 4).numpy())
    got = _traced(conv, data, np.int32(4))
    np.testing.assert_allclose(got, ref)


def test_for_range_start_stop_step():
    def fn(x):
        acc = x * 0
        for i in range(2, 10, 3):
            acc = acc + i
        return acc

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())   # 2 + 5 + 8 = 15
    np.testing.assert_allclose(ref, np.full((2,), 15.0, np.float32))
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_for_loop_var_value_after():
    def fn(x):
        for i in range(4):
            x = x + 1
        return x + i  # python leaves i == 3

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(np.asarray(conv(t(data)).numpy()), ref)


def test_for_over_list_stays_python():
    def fn(x):
        for w in [1.0, 2.0, 3.0]:
            x = x * w
        return x

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    np.testing.assert_allclose(_traced(conv, data),
                               np.full((2,), 6.0, np.float32))


# ---- logical ops (reference test_logical.py) ----

def test_logical_and_or_not():
    def fn(x):
        if x.sum() > 0 and x.max() < 10:
            y = x + 1
        elif x.sum() < -5 or not (x.min() > -100):
            y = x - 1
        else:
            y = x * 0
        return y

    conv = convert_function(fn)
    for v in (1.0, -3.0, -0.5):
        data = np.full((4,), v, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_short_circuit_preserved_for_python_values():
    def fn(flag, x, calls):
        def side():
            calls.append(1)
            return True
        if flag and side():
            return x + 1
        return x

    conv = convert_function(fn)
    assert getattr(conv, "_pt_dy2static", False)  # really converted
    data = np.zeros((2,), np.float32)
    calls = []
    out = conv(False, t(data), calls)
    np.testing.assert_allclose(np.asarray(out.numpy()), data)
    assert calls == []  # `and` must not evaluate side() when flag is False
    out = conv(True, t(data), calls)
    np.testing.assert_allclose(np.asarray(out.numpy()), data + 1)
    assert calls == [1]


# ---- integration through to_static ----

def test_to_static_data_dependent_branch():
    @to_static
    def fn(x):
        if x.mean() > 0:
            return x * 2
        return x * -1

    pos = np.ones((3,), np.float32)
    neg = -np.ones((3,), np.float32)
    np.testing.assert_allclose(np.asarray(fn(t(pos))[0].numpy()
                                          if isinstance(fn(t(pos)), tuple)
                                          else fn(t(pos)).numpy()), pos * 2)
    np.testing.assert_allclose(np.asarray(fn(t(neg)).numpy()), neg * -1)


def test_to_static_layer_forward_converted():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            y = self.fc(x)
            if y.mean() > 0:
                return y + 1
            return y - 1

    paddle.seed(0)
    net = Net()
    static_net = to_static(net)
    data = np.ones((2, 4), np.float32)
    eager_ref = net(t(data))  # converted forward, eager values
    got = static_net(t(data))
    np.testing.assert_allclose(np.asarray(got.numpy()),
                               np.asarray(eager_ref.numpy()), rtol=1e-6)
    assert getattr(net.forward.__func__, "_pt_dy2static", False)


def test_fluid_style_training_script_unmodified():
    """The VERDICT acceptance case: a fluid-era script whose loss path has a
    data-dependent `if` runs under to_static unmodified."""
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x, y):
            pred = self.fc(x)
            err = pred - y
            # huber-style data-dependent branch over a traced scalar
            if err.abs().mean() > 1.0:
                loss = err.abs().mean()
            else:
                loss = (err * err).mean()
            return loss

    paddle.seed(0)
    net = Net()
    fn = to_static(net)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    loss_small = float(fn(t(x * 0.01), t(y * 0.01)).numpy())
    loss_big = float(fn(t(x * 100), t(y * 100)).numpy())
    ref_small = float(net(t(x * 0.01), t(y * 0.01)).numpy())
    ref_big = float(net(t(x * 100), t(y * 100)).numpy())
    np.testing.assert_allclose(loss_small, ref_small, rtol=1e-5)
    np.testing.assert_allclose(loss_big, ref_big, rtol=1e-5)


def test_unconvertible_closure_warns_when_control_flow():
    k = 3.0

    def fn(x):
        if x.sum() > 0:
            return x * k
        return x

    with pytest.warns(UserWarning, match="dy2static"):
        conv = convert_function(fn)
    assert conv is fn  # fell back


def test_transformed_source_is_recorded():
    def fn(x):
        if x.sum() > 0:
            y = x + 1
        else:
            y = x - 1
        return y

    conv = convert_function(fn)
    src = conv._pt_transformed_source
    assert "_jst.run_ifelse" in src
    assert "if " not in src.replace("elif", "")  # the If is gone


# ---- break/continue (reference test_break_continue.py patterns) ----

def test_while_break_converts_and_traces():
    def fn(x):
        i = x.sum() * 0
        while i < 100:
            x = x * 2
            i = i + 1
            if x.sum() > 50:
                break
        return x, i

    conv = convert_function(fn)
    assert getattr(conv, "_pt_dy2static", False)
    data = np.ones((2,), np.float32)
    ref_x, ref_i = fn(t(data))
    got_x, got_i = _traced(conv, data)
    np.testing.assert_allclose(got_x, np.asarray(ref_x.numpy()))
    np.testing.assert_allclose(got_i, np.asarray(ref_i.numpy()))
    assert "_pt_brk" in conv._pt_transformed_source.replace("__pt_brk", "_pt_brk")


def test_while_break_skips_trailing_statements():
    def fn(x):
        acc = x * 0
        i = x.sum() * 0
        while i < 10:
            if i >= 3:
                break
            acc = acc + x   # must NOT run on the breaking iteration
            i = i + 1
        return acc, i

    conv = convert_function(fn)
    data = np.full((2,), 2.0, np.float32)
    ref_acc, ref_i = fn(t(data))
    assert float(ref_i.numpy()[()] if ref_i.numpy().shape == ()
                 else ref_i.numpy()) == 3.0
    got_acc, got_i = _traced(conv, data)
    np.testing.assert_allclose(got_acc, np.asarray(ref_acc.numpy()))
    np.testing.assert_allclose(got_i, np.asarray(ref_i.numpy()))


def test_while_continue_converts():
    def fn(x):
        acc = x * 0
        i = x.sum() * 0
        while i < 6:
            i = i + 1
            if i.sum() % 2 == 0:
                continue
            acc = acc + i  # odd iterations only: 1 + 3 + 5 = 9
        return acc

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(ref, 9.0)
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_for_range_break_converts():
    def fn(x):
        for i in range(100):
            x = x + 1
            if x.sum() > 10:
                break
        return x

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_for_range_continue_converts():
    def fn(x):
        for i in range(6):
            if i % 2 == 0:
                continue
            x = x + i   # 1 + 3 + 5
        return x

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(ref, 9.0)
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_nested_loop_break_binds_inner():
    def fn(x):
        total = x * 0
        i = x.sum() * 0
        while i < 3:
            j = x.sum() * 0
            while j < 10:
                if j >= 2:
                    break   # binds the INNER loop only
                total = total + 1
                j = j + 1
            i = i + 1
        return total  # 3 outer iterations x 2 inner adds = 6

    conv = convert_function(fn)
    data = np.zeros((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(ref, 6.0)
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_if_inside_while_carries_branch_assignments():
    """An `if` inside a `while` assigns through converted closures; those
    names must still ride the loop carry (regression: _assigned_names
    descends into generated closures' nonlocal lists)."""
    def fn(x):
        y = x * 0
        i = x.sum() * 0
        while i < 4:
            if i.sum() % 2 == 0:
                y = y + x       # even iterations: i = 0, 2
            else:
                y = y - x * 10  # odd iterations: i = 1, 3
            i = i + 1
        return y  # 2x - 20x = -18x

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    ref = np.asarray(fn(t(data)).numpy())
    np.testing.assert_allclose(ref, -18.0)
    np.testing.assert_allclose(_traced(conv, data), ref)


def test_break_then_fresh_temporary_traces():
    """A temporary first assigned AFTER a conditional break (the guarded
    tail) must not break tracing (lenient merge on generated guards)."""
    def fn(x):
        i = x.sum() * 0
        while i < 10:
            if i >= 3:
                break
            y = x + 1
            i = i + y.sum() * 0 + 1
        return x, i

    conv = convert_function(fn)
    data = np.ones((2,), np.float32)
    ref_x, ref_i = fn(t(data))
    got_x, got_i = _traced(conv, data)
    np.testing.assert_allclose(got_x, np.asarray(ref_x.numpy()))
    np.testing.assert_allclose(got_i, np.asarray(ref_i.numpy()))


def test_break_inside_try_keeps_loop_python_but_converts_rest():
    """break under try/with cannot become a flag; that LOOP stays python
    while the rest of the function still converts (no whole-function
    fallback via generated-module SyntaxError)."""
    def fn(x):
        i = 0
        while i < 10:
            try:
                i = i + 1
                if i >= 3:
                    break
            except ValueError:
                break
        if x.sum() > 0:      # this if must still convert
            return x * 2
        return x * -1

    conv = convert_function(fn)
    assert getattr(conv, "_pt_dy2static", False), "conversion fell back"
    src = conv._pt_transformed_source
    assert "break" in src          # the try-loop kept python semantics
    assert "_jst.ret_ifelse" in src  # the trailing if converted
    for data in (np.ones((2,), np.float32), -np.ones((2,), np.float32)):
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_enable_to_static_toggle():
    """paddle.jit.enable_to_static(False) must disable the AST pass
    (ProgramTranslator.enable contract)."""
    from paddle_tpu.jit import enable_to_static

    def fn(x):
        if x.sum() > 0:
            return x + 1
        return x - 1

    try:
        enable_to_static(False)
        off = convert_function(fn)
        assert off is fn  # untouched
    finally:
        enable_to_static(True)
    on = convert_function(fn)
    assert getattr(on, "_pt_dy2static", False)

    # the reference contract: the switch affects ALREADY-decorated
    # functions' subsequent (eager) calls — the dispatcher is live
    neg = t(-np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(on(neg).numpy()), -2.0)
    try:
        enable_to_static(False)
        # disabled: runs the ORIGINAL python fn (same eager result here,
        # but via fn itself — observable through the converted marker)
        assert on._pt_converted is not fn
        np.testing.assert_allclose(np.asarray(on(neg).numpy()),
                                   np.asarray(fn(neg).numpy()))
    finally:
        enable_to_static(True)


def test_tensor_iteration_terminates():
    """`for row in tensor` must iterate shape[0] rows and STOP — the
    __getitem__ fallback never raises IndexError under jnp's clipping
    semantics, so Tensor defines __iter__ (regression)."""
    data = np.arange(6, dtype=np.float32).reshape(3, 2)
    rows = [np.asarray(r.numpy()) for r in t(data)]
    assert len(rows) == 3
    np.testing.assert_allclose(np.stack(rows), data)

    def fn(x):
        acc = x.sum() * 0
        for row in x:
            acc = acc + row.sum()
        return acc

    ref = float(np.asarray(fn(t(data)).numpy()))
    assert ref == 15.0
    got = _traced(convert_function(fn), data)
    np.testing.assert_allclose(got, ref)

    with pytest.raises(TypeError, match="0-d"):
        next(iter(t(np.float32(1.0))))


# ---- reference ifelse_simple_func.py ports (2.x API) ----

def test_ref_if_else_with_optional_label():
    """dyfunc_with_if_else: tensor-cond if + python `is not None` if with
    an early return."""
    def fn(x_v, label=None):
        if x_v.mean() > 5:
            x_v = x_v - 1
        else:
            x_v = x_v + 1
        if label is not None:
            return ((x_v - label) ** 2).mean()
        return x_v

    conv = convert_function(fn)
    for base in (10.0, 0.0):
        data = np.full((4,), base, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)
    # label path (python-None dispatch must survive conversion)
    lab = np.zeros((4,), np.float32)
    ref = float(np.asarray(fn(t(np.full((4,), 10.0, np.float32)),
                              t(lab)).numpy()))
    got = _traced(conv, np.full((4,), 10.0, np.float32), lab)
    np.testing.assert_allclose(got, ref)


def test_ref_nested_three_levels_mixed_conditions():
    """nested_if_else: python shape conditions mixed with tensor-mean
    conditions across three nesting levels."""
    def fn(x_v):
        batch_size = 16
        feat = x_v.shape[-1]
        bias = x_v.sum() * 0 + 1
        if x_v.shape[0] != batch_size:   # python condition
            batch_size = x_v.shape[0]
        if x_v.mean() < 0:               # tensor condition
            y = x_v + bias
            w = x_v * 0 + 10
            if y.sum() < 10:             # tensor condition
                y = (y * w).abs()
            else:
                y = y - 1
        else:
            y = x_v - bias
        return y

    conv = convert_function(fn)
    for base in (-1.0, -0.001, 3.0):
        data = np.full((4, 3), base, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref, rtol=1e-6)


def test_ref_if_with_and_or_mixed_python_tensor():
    """if_with_and_or: `is not None` / python bools / tensor conditions in
    one and/or chain (short-circuit keeps the python parts python)."""
    def fn(x_v, label=None):
        if x_v is not None and (x_v.mean() > 0 or label is not None) \
                and x_v.shape[0] > 1 and True:
            x_v = x_v - 1
        else:
            x_v = x_v + 1
        return x_v

    conv = convert_function(fn)
    for base in (2.0, -2.0):
        data = np.full((4,), base, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_ref_if_with_class_var():
    """if_with_class_var: object attributes inside condition and body."""
    def fn(x):
        class Foo:
            def __init__(self):
                self.a = 1.0
                self.b = 2.0

        foo = Foo()
        if x.mean() > foo.a:
            x = x + foo.b
        else:
            x = x - foo.b
        return x

    conv = convert_function(fn)
    for base in (3.0, 0.0):
        data = np.full((4,), base, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(_traced(conv, data), ref)


def test_ref_net_with_control_flow_forward():
    """The reference's NetWithControlFlowIf shape: a Layer whose forward
    picks different sublayers per branch, trained through to_static."""
    class Net(nn.Layer):
        def __init__(self, d=8):
            super().__init__()
            self.hot = nn.Linear(d, d)
            self.cold = nn.Linear(d, d)
            self.alpha = 10.0

        def forward(self, x):
            h = x
            if h.mean() > 0:
                out = self.hot(h) + self.alpha
            else:
                out = self.cold(h) - self.alpha
            return out.mean()

    paddle.seed(0)
    net = Net()
    static_net = to_static(net)
    for base in (1.0, -1.0):
        data = np.full((2, 8), base, np.float32)
        ref = float(np.asarray(net(t(data)).numpy()))
        got = float(np.asarray(static_net(t(data)).numpy()))
        np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_augassign_after_if_keeps_merge_name():
    """`c += 3` after an if that defines c must count as a USE of c — an
    AugAssign target reads its name even with Store ctx (regression)."""
    def fn(x):
        if x.mean() > 0:
            c = x * 1.0
        else:
            c = x * 2.0
        c += 3
        return c

    conv = convert_function(fn)
    for base in (1.0, -1.0):
        data = np.full((3,), base, np.float32)
        ref = np.asarray(fn(t(data)).numpy())
        np.testing.assert_allclose(np.asarray(conv(t(data)).numpy()), ref)
        np.testing.assert_allclose(_traced(conv, data), ref)
