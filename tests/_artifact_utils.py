"""Shared test helper: walk a .pdweights (PDW1) artifact and return the
per-tensor PJRT type codes — used by the quantization and C++ predictor
suites to assert int8 weights really reach the serving artifact."""
import struct


def parse_pdweights_types(path):
    raw = open(path, "rb").read()
    assert raw[:4] == b"PDW1"
    (count,) = struct.unpack_from("<I", raw, 4)
    off, codes = 8, []
    for _ in range(count):
        code, ndim = struct.unpack_from("<II", raw, off)
        off += 8 + 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", raw, off)
        off += 8 + nbytes
        codes.append(code)
    assert off == len(raw)
    return codes
