"""Fused Pallas kernel parity tests (layernorm; reference:
operators/layer_norm_op.cu + fused/ layernorm family).

Run in interpret mode on the CPU mesh; the same kernels compile for TPU.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.layernorm import eligible, fused_layer_norm


def _ref_ln(x, w, b, eps):
    h = x.astype(jnp.float32)
    mu = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(h - mu), axis=-1, keepdims=True)
    out = (h - mu) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(
        x.dtype)


@pytest.mark.parametrize("shape", [(16, 256), (2, 8, 128), (32, 384)])
def test_fused_layer_norm_forward(shape):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    w = jnp.asarray(rng.randn(shape[-1]).astype(np.float32))
    b = jnp.asarray(rng.randn(shape[-1]).astype(np.float32))
    got = fused_layer_norm(x, w, b, 1e-5, force_pallas=True)
    want = _ref_ln(x, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_fused_layer_norm_grads():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(24, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    g = jnp.asarray(rng.randn(24, 128).astype(np.float32))

    def loss_fused(x, w, b):
        return jnp.sum(fused_layer_norm(x, w, b, 1e-5, force_pallas=True) * g)

    def loss_ref(x, w, b):
        return jnp.sum(_ref_ln(x, w, b, 1e-5) * g)

    gx1, gw1, gb1 = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    gx2, gw2, gb2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(gb1), np.asarray(gb2),
                               atol=1e-4, rtol=1e-4)


def test_fused_layer_norm_bf16_dtype():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 128).astype(np.float32)).astype(
        jnp.bfloat16)
    w = jnp.ones((128,), jnp.bfloat16)
    b = jnp.zeros((128,), jnp.bfloat16)
    got = fused_layer_norm(x, w, b, 1e-5, force_pallas=True)
    assert got.dtype == jnp.bfloat16
    want = _ref_ln(x, w, b, 1e-5)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2)


def test_eligibility_gate():
    assert eligible((16, 256), 1, True, True)
    assert not eligible((16, 200), 1, True, True)      # lane-misaligned
    assert not eligible((16, 256), 2, True, True)      # multi-axis norm
    assert not eligible((16, 256), 1, True, False)     # no bias
    assert not eligible((3, 256), 1, True, True)       # rows not tileable
    assert not eligible((256,), 1, True, True)         # 1-D input


def test_functional_layer_norm_uses_same_math():
    # nn.functional.layer_norm routes through the fused module's fallback on
    # CPU — value parity with the explicit reference
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(4, 8, 256).astype(np.float32))
    w = paddle.to_tensor(rng.randn(256).astype(np.float32))
    b = paddle.to_tensor(rng.randn(256).astype(np.float32))
    y = F.layer_norm(x, 256, weight=w, bias=b)
    want = _ref_ln(x.data, w.data, b.data, 1e-5)
    np.testing.assert_allclose(np.asarray(y.data), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


# ---------------- fused adam (ops/fused_adam.py) ----------------

from paddle_tpu.ops.fused_adam import fused_adam


def _ref_adam(p, g, m1, m2, lr, b1p, b2p, wd, b1, b2, eps, decoupled):
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not decoupled:
        g = g + wd * p32
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    upd = (m1n / (1 - b1p)) / (jnp.sqrt(m2n / (1 - b2p)) + eps)
    if decoupled:
        upd = upd + wd * p32
    return (p32 - lr * upd).astype(p.dtype), m1n, m2n


@pytest.mark.parametrize("n,decoupled", [(2048, False), (2048, True),
                                         (1500, False), (4099, True)])
def test_fused_adam_parity(n, decoupled):
    rng = np.random.RandomState(4)
    p = jnp.asarray(rng.randn(n).astype(np.float32))
    g = jnp.asarray(rng.randn(n).astype(np.float32))
    m1 = jnp.asarray(rng.randn(n).astype(np.float32)) * 0.1
    m2 = jnp.abs(jnp.asarray(rng.randn(n).astype(np.float32))) * 0.01
    args = dict(beta1=0.9, beta2=0.999, epsilon=1e-8, decoupled=decoupled)
    got = fused_adam(p, g, m1, m2, 1e-3, 0.9, 0.999, 0.01,
                     force_pallas=True, **args)
    want = _ref_adam(p, g, m1, m2, 1e-3, 0.9, 0.999, 0.01, 0.9, 0.999,
                     1e-8, decoupled)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6,
                                   rtol=1e-6)


def test_fused_adam_bf16_param():
    rng = np.random.RandomState(5)
    p = jnp.asarray(rng.randn(2048).astype(np.float32)).astype(jnp.bfloat16)
    g = jnp.asarray(rng.randn(2048).astype(np.float32)).astype(jnp.bfloat16)
    m1 = jnp.zeros(2048, jnp.float32)
    m2 = jnp.zeros(2048, jnp.float32)
    newp, m1n, m2n = fused_adam(p, g, m1, m2, 1e-3, 0.9, 0.999, 0.0,
                                beta1=0.9, beta2=0.999, epsilon=1e-8,
                                decoupled=False, force_pallas=True)
    assert newp.dtype == jnp.bfloat16
    assert m1n.dtype == jnp.float32
    wantp, _, _ = _ref_adam(p, g, m1, m2, 1e-3, 0.9, 0.999, 0.0, 0.9,
                            0.999, 1e-8, False)
    np.testing.assert_allclose(np.asarray(newp, np.float32),
                               np.asarray(wantp, np.float32),
                               atol=1e-2, rtol=1e-2)


def test_adam_optimizer_matches_unfused_rule():
    # the Adam._rule fused dispatch must not change training numerics: run
    # two steps through the optimizer on CPU (falls back to _adam_math,
    # which the pallas kernel mirrors exactly) and compare against the
    # hand-rolled reference sequence
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as optim

    rng = np.random.RandomState(6)
    w0 = rng.randn(64, 32).astype(np.float32)
    lin = paddle.nn.Linear(64, 32)
    lin.weight.set_value(w0)
    opt = optim.Adam(learning_rate=1e-2, parameters=lin.parameters())
    x = paddle.to_tensor(rng.randn(8, 64).astype(np.float32))
    for _ in range(2):
        loss = paddle.mean(lin(x) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()

    p = jnp.asarray(w0)
    bias = jnp.zeros((32,), jnp.float32)
    m1 = jnp.zeros_like(p)
    m2 = jnp.zeros_like(p)
    bm1 = jnp.zeros_like(bias)
    bm2 = jnp.zeros_like(bias)
    b1p = b2p = 1.0
    xv = jnp.asarray(x.numpy())
    for _ in range(2):
        def loss_fn(w, b):
            return jnp.mean((xv @ w + b) ** 2)
        gw, gb = jax.grad(loss_fn, argnums=(0, 1))(p, bias)
        b1p, b2p = b1p * 0.9, b2p * 0.999
        p, m1, m2 = _ref_adam(p, gw, m1, m2, 1e-2, b1p, b2p, 0.0, 0.9,
                              0.999, 1e-8, False)
        bias, bm1, bm2 = _ref_adam(bias, gb, bm1, bm2, 1e-2, b1p, b2p, 0.0,
                                   0.9, 0.999, 1e-8, False)
    np.testing.assert_allclose(np.asarray(lin.weight.numpy()),
                               np.asarray(p), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lin.bias.numpy()),
                               np.asarray(bias), atol=1e-5, rtol=1e-5)
