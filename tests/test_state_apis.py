"""State-mutating public APIs must work or raise — never silently no-op.

(VERDICT r1: fleet.save_persistables/save_inference_model were `pass`,
static.save/load were `pass`, fleet.util collectives returned their input.)
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.distributed import fleet


def _fleet():
    fleet.init(is_collective=True)
    return fleet.fleet()


def test_save_persistables_roundtrip(tmp_path):
    f = _fleet()
    model = nn.Linear(4, 2)
    f.save_persistables(dirname=str(tmp_path), main_program=model)
    path = os.path.join(str(tmp_path), "persistables")
    assert os.path.exists(path)
    from paddle_tpu.framework_io import load
    state = load(path)
    np.testing.assert_allclose(np.asarray(state["weight"]),
                               model.weight.numpy())


def test_save_persistables_raises_without_model(tmp_path):
    f = fleet.Fleet()
    with pytest.raises(RuntimeError):
        f.save_persistables(dirname=str(tmp_path))


def test_save_inference_model_writes_artifact(tmp_path):
    f = _fleet()
    model = nn.Linear(4, 2)
    f.save_inference_model(dirname=str(tmp_path), main_program=model)
    assert os.path.exists(os.path.join(str(tmp_path), "model.pdparams"))


def test_static_save_load_roundtrip(tmp_path):
    model = nn.Linear(3, 3)
    fn = paddle.jit.to_static(model)
    path = str(tmp_path / "m")
    static.save(fn, path)
    w0 = model.weight.numpy().copy()
    model.weight.set_value(np.zeros_like(w0))
    static.load(fn, path)
    np.testing.assert_allclose(model.weight.numpy(), w0)


def test_static_save_rejects_placeholder_program():
    with pytest.raises(TypeError):
        static.save(static.default_main_program(), "/tmp/nope")
    with pytest.raises(TypeError):
        static.load(static.default_main_program(), "/tmp/nope")


def test_static_save_inference_model_exports_servable(tmp_path):
    model = nn.Linear(4, 2)
    path = str(tmp_path / "served")
    spec = static.InputSpec([1, 4], "float32")
    static.save_inference_model(path, [spec], None, None, program=model)
    from paddle_tpu import inference
    cfg = inference.Config(path)
    pred = inference.create_predictor(cfg)
    h = pred.get_input_handle(pred.get_input_names()[0])
    x = np.ones((1, 4), np.float32)
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(
        out, x @ model.weight.numpy() + model.bias.numpy(), rtol=1e-5)


def test_static_save_inference_model_rejects_placeholder():
    with pytest.raises(TypeError):
        static.save_inference_model("/tmp/nope", [], None, None)


def test_util_collectives_single_process():
    f = _fleet()
    # world size 1: identity semantics are exact, not a stub
    assert f.util.all_gather(np.arange(3)) is not None
    out = f.util.all_reduce(np.arange(3), mode="sum")
    np.testing.assert_allclose(np.asarray(out), np.arange(3))
    assert f.util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]


def test_distributed_scaler_wraps_and_steps():
    from paddle_tpu import amp, optimizer
    f = _fleet()
    w = paddle.core.tensor.Parameter(np.array([1.0], np.float32))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w])
    scaler = f.distributed_scaler(amp.GradScaler(init_loss_scaling=8.0))
    loss = (w * 2.0).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(w.numpy(), [-1.0])


def test_random_sampler_governed_by_paddle_seed():
    """Shuffle order reproduces under paddle.seed and ignores numpy's
    module-global RNG (the cross-test coupling that made hapi fit()
    accuracy order-dependent)."""
    import numpy as np
    from paddle_tpu.io import RandomSampler

    class _DS:
        def __len__(self):
            return 12

    paddle.seed(7)
    a = list(iter(RandomSampler(_DS())))
    np.random.seed(99)  # unrelated global-state churn
    paddle.seed(7)
    b = list(iter(RandomSampler(_DS())))
    assert a == b
    assert sorted(a) == list(range(12))
