"""RNN-Transducer loss (warprnnt analog; VERDICT r3 op-zoo tail).
Ground truth: brute-force enumeration of every monotone alignment path."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _brute_force_nll(logits, label, T, U, blank):
    """-log P(label | logits): sum over all paths of T blanks + U label
    emissions. A path is a choice of which u-level each blank is emitted
    at; equivalently an interleaving of T 'advance t' (blank) moves and U
    'advance u' (label) moves, ending with the final blank at (T-1, U)."""
    V = logits.shape[-1]
    lp = logits.astype(np.float64)
    lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
    total = -np.inf
    # choose the positions of the U label moves among the first T+U-1
    # moves... enumerate move strings directly: sequences of 'b'*T+'l'*U
    # where the LAST move must be the final blank; i.e. all interleavings
    # of (T-1) blanks + U labels, then the closing blank.
    moves = ["b"] * (T - 1) + ["l"] * U
    for perm in set(itertools.permutations(moves)):
        t = u = 0
        path_lp = 0.0
        for mv in perm:
            if mv == "b":
                path_lp += lp[t, u, blank]
                t += 1
            else:
                path_lp += lp[t, u, label[u]]
                u += 1
        path_lp += lp[T - 1, U, blank]  # closing blank
        total = np.logaddexp(total, path_lp)
    return -total


def test_rnnt_loss_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, U, V = 2, 4, 2, 3
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = rng.randint(1, V, (B, U)).astype(np.int32)
    loss = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(label),
                       paddle.to_tensor(np.full(B, T, np.int32)),
                       paddle.to_tensor(np.full(B, U, np.int32)),
                       blank=0, fastemit_lambda=0.0, reduction="none")
    got = np.asarray(loss.data)
    for b in range(B):
        ref = _brute_force_nll(logits[b], label[b], T, U, 0)
        np.testing.assert_allclose(got[b], ref, rtol=1e-5, atol=1e-5)


def test_rnnt_loss_variable_lengths():
    """Padded samples must score identically to their trimmed versions."""
    rng = np.random.RandomState(1)
    T, U, V = 5, 3, 4
    logits = rng.randn(1, T, U + 1, V).astype(np.float32)
    label = rng.randint(1, V, (1, U)).astype(np.int32)
    t_eff, u_eff = 3, 2
    loss_pad = F.rnnt_loss(
        paddle.to_tensor(logits), paddle.to_tensor(label),
        paddle.to_tensor(np.array([t_eff], np.int32)),
        paddle.to_tensor(np.array([u_eff], np.int32)),
        fastemit_lambda=0.0, reduction="none")
    ref = _brute_force_nll(logits[0, :t_eff, :u_eff + 1], label[0],
                           t_eff, u_eff, 0)
    np.testing.assert_allclose(np.asarray(loss_pad.data)[0], ref,
                               rtol=1e-5, atol=1e-5)


def test_rnnt_loss_grad_finite_difference():
    rng = np.random.RandomState(2)
    B, T, U, V = 1, 3, 2, 3
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = rng.randint(1, V, (B, U)).astype(np.int32)
    ilen = np.full(B, T, np.int32)
    ulen = np.full(B, U, np.int32)

    def loss_of(lg):
        t = paddle.to_tensor(lg)
        t.stop_gradient = False
        loss = F.rnnt_loss(t, paddle.to_tensor(label),
                           paddle.to_tensor(ilen), paddle.to_tensor(ulen),
                           fastemit_lambda=0.0, reduction="sum")
        return loss, t

    loss, t = loss_of(logits)
    loss.backward()
    analytic = np.asarray(t.grad.data)
    eps = 1e-3
    flat = logits.reshape(-1)
    for i in rng.choice(flat.size, 10, replace=False):
        up, dn = flat.copy(), flat.copy()
        up[i] += eps
        dn[i] -= eps
        lu, _ = loss_of(up.reshape(logits.shape))
        ld, _ = loss_of(dn.reshape(logits.shape))
        num = (float(lu.item()) - float(ld.item())) / (2 * eps)
        np.testing.assert_allclose(analytic.reshape(-1)[i], num,
                                   rtol=5e-3, atol=5e-3)


def test_rnnt_loss_fastemit_scales_label_grads_only():
    rng = np.random.RandomState(3)
    B, T, U, V = 1, 3, 2, 3
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = np.array([[1, 2]], np.int32)
    ilen = np.full(B, T, np.int32)
    ulen = np.full(B, U, np.int32)

    def grad_with(lam):
        t = paddle.to_tensor(logits)
        t.stop_gradient = False
        F.rnnt_loss(t, paddle.to_tensor(label), paddle.to_tensor(ilen),
                    paddle.to_tensor(ulen), fastemit_lambda=lam,
                    reduction="sum").backward()
        return np.asarray(t.grad.data)

    g0 = grad_with(0.0)
    g1 = grad_with(0.5)
    # label-emission entries scaled by 1.5; everything else untouched
    for u in range(U):
        v = label[0, u]
        np.testing.assert_allclose(g1[0, :, u, v], 1.5 * g0[0, :, u, v],
                                   rtol=1e-5)
    np.testing.assert_allclose(g1[0, :, :, 0], g0[0, :, :, 0], rtol=1e-6)
    np.testing.assert_allclose(g1[0, :, U, :], g0[0, :, U, :], rtol=1e-6)


def test_rnnt_loss_layer_and_reductions():
    from paddle_tpu.nn import RNNTLoss
    rng = np.random.RandomState(4)
    B, T, U, V = 3, 3, 2, 4
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    label = rng.randint(1, V, (B, U)).astype(np.int32)
    ilen = paddle.to_tensor(np.full(B, T, np.int32))
    ulen = paddle.to_tensor(np.full(B, U, np.int32))
    none = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(label),
                       ilen, ulen, reduction="none")
    mean = RNNTLoss()(paddle.to_tensor(logits), paddle.to_tensor(label),
                      ilen, ulen)
    ssum = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(label),
                       ilen, ulen, reduction="sum")
    n = np.asarray(none.data)
    assert n.shape == (B,) and np.all(n > 0)
    np.testing.assert_allclose(float(mean.item()), n.mean(), rtol=1e-6)
    np.testing.assert_allclose(float(ssum.item()), n.sum(), rtol=1e-6)
