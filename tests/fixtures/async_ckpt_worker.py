"""Subprocess fixture for tests/test_async_checkpoint.py: runs
ResilientTrainer over an AsyncCheckpointManager with exact-resume cursor
hooks, so the parent test can kill it mid-background-persist (or SIGTERM
it) and assert that a fresh process resumes BIT-IDENTICALLY.

    python async_ckpt_worker.py WORKDIR MODE

modes:
    fast    train NUM_STEPS (env, default 8) steps back-to-back
    slow    sleep 0.15s inside every step — gives the parent a window to
            deliver SIGTERM mid-run (emergency-save test)

env knobs: NUM_STEPS, SNAP_INTERVAL (save_interval, default 2), and the
fault schedule via PDTPU_FAULTS (kill@N:persist, ckpt_torn_write@N, ...).

The data stream is a np.random.RandomState(7) batch generator whose
cursor (next index + full RNG state) rides in the checkpoint manifest via
get_cursor/set_cursor; batch() ASSERTS the requested index matches the
cursor, so any resume that fails to rewind the stream crashes loudly
instead of silently training on wrong data.

Every completed step appends {"step", "loss"} to WORKDIR/losses.jsonl
(flushed + fsynced so a SIGKILL can't lose lines). The parent stitches
the killed + resumed runs' lines together: every recording of a given
step — across processes, including replays — must be bit-identical, and
must equal the uninterrupted run's value.

Writes WORKDIR/progress (one line per step) and WORKDIR/report.json on a
clean finish. Exit codes: 0 done, 137 fault-injected SIGKILL, 143
preempted.
"""
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn, optimizer  # noqa: E402
from paddle_tpu.checkpoint import (  # noqa: E402
    AsyncCheckpointManager, restore_rng, rng_cursor)
from paddle_tpu.distributed.resilient import (  # noqa: E402
    ResilientConfig, ResilientTrainer)

WORKDIR = sys.argv[1]
MODE = sys.argv[2] if len(sys.argv) > 2 else "fast"
NUM_STEPS = int(os.environ.get("NUM_STEPS", "8"))
SNAP_INTERVAL = int(os.environ.get("SNAP_INTERVAL", "2"))
LOSSES = os.path.join(WORKDIR, "losses.jsonl")
PROGRESS = os.path.join(WORKDIR, "progress")
REPORT = os.path.join(WORKDIR, "report.json")


class Stream:
    """Deterministic batch stream with an exact-resume cursor."""

    def __init__(self):
        self.rs = np.random.RandomState(7)
        self.next = 0

    def batch(self, i):
        assert i == self.next, \
            f"stream asked for batch {i} but cursor is at {self.next}"
        x = self.rs.randn(8, 4).astype(np.float32)
        y = self.rs.randn(8, 4).astype(np.float32)
        self.next = i + 1
        return paddle.to_tensor(x), paddle.to_tensor(y)

    def cursor(self):
        return {"next": self.next, **rng_cursor(self.rs)}

    def set(self, cur):
        self.next = int(cur["next"])
        restore_rng(self.rs, cur)


def main():
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    stream = Stream()
    current = {"i": None}  # batch index of the in-flight step

    def batch_fn(i):
        current["i"] = i
        return stream.batch(i)

    def train_fn(x, y):
        if MODE == "slow":
            time.sleep(0.15)
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        with open(LOSSES, "a") as f:
            f.write(json.dumps({"step": current["i"],
                                "loss": float(loss.item())}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        with open(PROGRESS, "a") as f:
            f.write("step\n")
        return loss

    ckpt = AsyncCheckpointManager(os.path.join(WORKDIR, "ckpt"),
                                  max_to_keep=50)
    trainer = ResilientTrainer(
        train_fn, ckpt,
        get_state=lambda: {"model": model.state_dict()},
        set_state=lambda s: model.set_state_dict(s["model"]),
        get_cursor=stream.cursor,
        set_cursor=stream.set,
        config=ResilientConfig(save_interval=SNAP_INTERVAL))
    summary = trainer.run(batch_fn, num_steps=NUM_STEPS)

    kinds = [e["kind"] for e in summary["events"]]
    resumed_from = next((e["step"] for e in summary["events"]
                         if e["kind"] == "resumed"), 0)
    with open(REPORT, "w") as f:
        json.dump({"resumed_from": resumed_from,
                   "completed": summary["completed_steps"],
                   "event_kinds": kinds,
                   "quarantined": [
                       {"step": e["step"], "file": e["file"],
                        "reason": e["reason"]}
                       for e in summary["events"]
                       if e["kind"] == "ckpt_quarantined"],
                   "ckpt": summary["checkpoint"]}, f)
    ckpt.close()


if __name__ == "__main__":
    main()
