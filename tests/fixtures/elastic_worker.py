"""Fixture for the elastic-launcher e2e test: under the ORIGINAL 2-node
membership it waits (simulating training that can't finish while a peer is
wedged); after the elastic manager detects the dead peer and relaunches with
a rewritten 1-node world, it completes."""
import json
import os
import sys
import time

world = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
out = sys.argv[1]

with open(out, "a") as f:
    f.write(json.dumps({"world": world, "rank": rank,
                        "endpoints": os.getenv("PADDLE_TRAINER_ENDPOINTS")})
            + "\n")

if world > 1:
    time.sleep(120)  # wait out the membership change; manager will kill us
