"""Fixture: fleet.util process-level collectives across real processes
(reference collective-op test pattern, test_collective_base.py)."""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.distributed.parallel_env import get_rank  # noqa: E402


def main():
    fleet.init(is_collective=True)
    rank = get_rank()
    util = fleet.fleet().util
    total = util.all_reduce(np.asarray(float(rank + 1)), mode="sum")
    gathered = util.all_gather(np.asarray(float(rank + 1)))
    print(json.dumps({
        "rank": rank,
        "sum": float(np.asarray(total)),
        "gathered": [float(np.asarray(g)) for g in gathered]}))


if __name__ == "__main__":
    main()
