"""Subprocess fixture for tests/test_llm_engine.py: runs a ServingServer
fronting an LLMEngine (gpt2-tiny, slot-paged KV pool) on an ephemeral
port, so the parent test can drive live /generate traffic and deliver
SIGTERM mid-decode to assert the LLM drain contract: admissions stop
(late requests get 503 or connection-refused), every ADMITTED sequence
still decodes to completion, the process exits 0, and the final metrics
snapshot reconciles with what the clients observed.

    python llm_serving_worker.py WORKDIR

env knobs:
    LLM_SLOTS     KV pool size (default 2)
    LLM_MAX_NEW   default max_new_tokens (default 12)

Writes WORKDIR/port once the socket is bound (the parent polls for it)
and WORKDIR/metrics_final.txt (Prometheus text) during drain. Exit 0 on
a clean drain.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import serving  # noqa: E402
from paddle_tpu.models.gpt import GPTForCausalLM  # noqa: E402

WORKDIR = sys.argv[1]
SLOTS = int(os.environ.get("LLM_SLOTS", "2"))
MAX_NEW = int(os.environ.get("LLM_MAX_NEW", "12"))


def main():
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    engine = serving.LLMEngine(
        model, serving.LLMEngineConfig(
            num_slots=SLOTS, block_len=8, n_blocks=8,
            max_new_tokens=MAX_NEW, max_queue_depth=64))
    engine.start()
    # warm the unified mixed prefill+decode step executable the traffic
    # will hit, so SIGTERM lands mid-decode rather than mid-compile;
    # then reset metrics so the final snapshot reconciles client-for-client
    engine.generate([1, 2, 3], max_new_tokens=2, timeout=300)
    engine.metrics = serving.LLMMetrics()
    engine.metrics.set_slots(0, engine.pool.num_slots)

    server = serving.ServingServer(
        llm_engine=engine, port=0,
        final_metrics_path=os.path.join(WORKDIR, "metrics_final.txt"))
    # socket bound at construction: write the handshake file atomically so
    # the parent never reads a half-written port
    tmp = os.path.join(WORKDIR, "port.tmp")
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, os.path.join(WORKDIR, "port"))
    server.serve_forever()  # installs SIGTERM/SIGINT drain handlers


if __name__ == "__main__":
    main()
