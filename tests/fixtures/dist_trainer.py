"""Trainer fixture for the TestDistBase analog (reference:
python/paddle/fluid/tests/unittests/test_dist_base.py — dist_mnist.py style).

Reads PADDLE_TRAINER_* env (the launch.py contract), initializes
jax.distributed when world > 1, trains a deterministic MLP on its batch shard
with eager DataParallel gradient sync, and prints one JSON line with the loss
trajectory so the parent test can assert 1-proc vs N-proc parity.
"""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn, optimizer  # noqa: E402
from paddle_tpu.distributed.data_parallel import DataParallel  # noqa: E402
from paddle_tpu.distributed.parallel_env import (get_rank, get_world_size,
                                                 init_parallel_env)  # noqa: E402


def main():
    init_parallel_env()
    rank, world = get_rank(), get_world_size()

    paddle.seed(0)  # identical init on every rank
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = DataParallel(model)
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=model.parameters())

    rng = np.random.RandomState(7)  # identical dataset on every rank
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)

    losses = []
    for step in range(5):
        xs = X[rank::world]  # deterministic shard
        ys = Y[rank::world]
        out = model(paddle.to_tensor(xs))
        loss = nn.functional.mse_loss(out, paddle.to_tensor(ys))
        loss.backward()
        model.apply_collective_grads()  # reducer parity: mean over ranks
        opt.step()
        opt.clear_grad()
        # report the FULL-batch loss so 1-proc and N-proc trajectories are
        # directly comparable (per-shard losses differ by construction)
        with paddle.no_grad():
            full = nn.functional.mse_loss(
                model(paddle.to_tensor(X)), paddle.to_tensor(Y))
        losses.append(float(full.item()))

    w = model.parameters()[0].numpy()
    print(json.dumps({"rank": rank, "world": world, "losses": losses,
                      "w_sum": float(np.abs(w).sum())}))


if __name__ == "__main__":
    main()
