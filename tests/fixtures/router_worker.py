"""Subprocess fixture for tests/test_router.py: runs a RouterServer over
TWO in-process LLMEngine replicas (gpt2-tiny) on an ephemeral port, with
a replica-tier fault plan taken from PDTPU_FAULTS — e.g.
`replica_crash@0` kills replica0 after the warmup reset, so the parent
can drive live /generate traffic across a real mid-traffic replica loss
and reconcile: every accepted request returns 200 with the full token
stream (zero dropped), and the router's /metrics account for the
quarantine + failovers client-for-client.

    python router_worker.py WORKDIR

env knobs:
    LLM_SLOTS             per-replica KV pool size (default 4)
    LLM_MAX_NEW           default max_new_tokens (default 8)
    ROUTER_FAULTS         replica-tier fault clauses (replica_crash@i, ...)
    ROUTER_FAULT_DELAY_S  arm the clauses this long after serving starts
                          (default 1.0) — the supervision loop polls the
                          plan every pump, so arming late is what makes
                          the loss land MID-traffic

Writes WORKDIR/port once the socket is bound (the parent polls for it)
and WORKDIR/metrics_final.txt (router Prometheus text) during drain.
Exit 0 on a clean drain.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import serving  # noqa: E402
from paddle_tpu.models.gpt import GPTForCausalLM  # noqa: E402
from paddle_tpu.utils.fault_injection import (FaultPlan,  # noqa: E402
                                              set_global_plan)

WORKDIR = sys.argv[1]
SLOTS = int(os.environ.get("LLM_SLOTS", "4"))
MAX_NEW = int(os.environ.get("LLM_MAX_NEW", "8"))
FAULTS = os.environ.get("ROUTER_FAULTS", "")
FAULT_DELAY_S = float(os.environ.get("ROUTER_FAULT_DELAY_S", "1.0"))


def main():
    paddle.seed(0)
    model = GPTForCausalLM.from_preset("gpt2-tiny")
    replicas = []
    for i in range(2):
        engine = serving.LLMEngine(
            model, serving.LLMEngineConfig(
                num_slots=SLOTS, block_len=8, n_blocks=8,
                max_new_tokens=MAX_NEW, max_queue_depth=64))
        # warm the unified step executable BEFORE handing the engine to
        # the router, so the injected replica loss lands mid-decode
        # rather than mid-compile
        engine.start()
        engine.generate([1, 2, 3], max_new_tokens=2, timeout=300)
        engine.metrics = serving.LLMMetrics()
        engine.metrics.set_slots(0, engine.pool.num_slots)
        # fault_plan=None: replicas poll the GLOBAL plan each pump, so
        # the timer below can arm the loss mid-traffic
        replicas.append(serving.InProcessReplica(engine, i))

    router = serving.ReplicaRouter(
        replicas, serving.RouterConfig(poll_interval_s=0.002))
    server = serving.RouterServer(router, port=0, request_timeout_s=120.0)
    server.start()   # supervision thread + HTTP thread

    if FAULTS:
        import threading as _t
        _t.Timer(FAULT_DELAY_S,
                 lambda: set_global_plan(
                     FaultPlan.from_spec(FAULTS))).start()

    # socket bound at construction: write the handshake file atomically so
    # the parent never reads a half-written port
    tmp = os.path.join(WORKDIR, "port.tmp")
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, os.path.join(WORKDIR, "port"))

    import signal
    import threading
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    done.wait()
    # drain contract: finish every admitted stream, snapshot metrics, exit 0
    server.stop(drain=True)
    tmp = os.path.join(WORKDIR, "metrics_final.tmp")
    with open(tmp, "w") as f:
        f.write(router.metrics.render())
    os.replace(tmp, os.path.join(WORKDIR, "metrics_final.txt"))


if __name__ == "__main__":
    main()
