"""Subprocess fixture for tests/test_resilient.py: runs ResilientTrainer
on a tiny model with the fault schedule taken from PDTPU_FAULTS, so the
parent test can kill it (or let the schedule kill it) and assert on what
a fresh process recovers.

    python resilient_worker.py WORKDIR MODE

modes:
    fast    train NUM_STEPS (env, default 6) steps back-to-back
    slow    sleep 0.15s inside every step — gives the parent a window to
            deliver SIGTERM mid-run (preemption test)

Writes WORKDIR/progress (one line per completed step, so the parent can
wait for the run to be mid-flight) and WORKDIR/report.json on a clean
finish. Exit codes: 0 done, 137 fault-injected SIGKILL, 143 preempted.
"""
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn, optimizer  # noqa: E402
from paddle_tpu.distributed.resilient import (  # noqa: E402
    ResilientConfig, ResilientTrainer)

WORKDIR = sys.argv[1]
MODE = sys.argv[2] if len(sys.argv) > 2 else "fast"
NUM_STEPS = int(os.environ.get("NUM_STEPS", "6"))
PROGRESS = os.path.join(WORKDIR, "progress")
REPORT = os.path.join(WORKDIR, "report.json")


def main():
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))

    def train_fn(_step_tag):
        if MODE == "slow":
            time.sleep(0.15)
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        with open(PROGRESS, "a") as f:
            f.write("step\n")
        return loss

    trainer = ResilientTrainer(
        train_fn, os.path.join(WORKDIR, "ckpt"),
        get_state=lambda: {"model": model.state_dict()},
        set_state=lambda s: model.set_state_dict(s["model"]),
        config=ResilientConfig(save_interval=1),
        use_orbax=False)
    resumed_from = trainer.ckpt.latest_step() or 0
    summary = trainer.run(lambda i: i, num_steps=NUM_STEPS)

    with open(REPORT, "w") as f:
        json.dump({"resumed_from": resumed_from,
                   "completed": summary["completed_steps"],
                   "event_kinds": [e["kind"] for e in summary["events"]]},
                  f)


if __name__ == "__main__":
    main()
