"""Fixture for the elastic-restart test: trains 6 steps with step-level
checkpointing; on the FIRST attempt it crashes hard at step 3. The launcher's
--max_restarts respawns it; the retry must resume from the checkpoint (not
step 0) and finish. Writes a JSON report for the parent test.

Checkpoints go through CheckpointManager's non-orbax fallback path so the
atomic-rename + integrity-manifest machinery is exercised under a real
process crash, not just in-process tests."""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn, optimizer  # noqa: E402
from paddle_tpu.checkpoint import CheckpointManager  # noqa: E402

WORKDIR = sys.argv[1]
MARKER = os.path.join(WORKDIR, "attempted")
CKPT = os.path.join(WORKDIR, "ckpt")
REPORT = os.path.join(WORKDIR, "report.json")


def main():
    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))

    mgr = CheckpointManager(CKPT, max_to_keep=10, use_orbax=False)
    start_step = mgr.latest_step() or 0
    if start_step:
        state = mgr.restore(start_step)
        model.set_state_dict(state["model"])

    first_attempt = not os.path.exists(MARKER)
    with open(MARKER, "a") as f:
        f.write("x\n")

    steps_this_run = []
    for step in range(start_step, 6):
        loss = nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        mgr.save(step + 1, {"model": model.state_dict()})
        steps_this_run.append(step)
        if first_attempt and step == 2:
            os._exit(17)  # simulated hard crash mid-training

    with open(REPORT, "w") as f:
        json.dump({"resumed_from": start_step,
                   "steps_this_run": steps_this_run,
                   "attempts": sum(1 for _ in open(MARKER))}, f)


if __name__ == "__main__":
    main()
