"""Subprocess fixture for tests/test_serving.py: runs a ServingServer on an
ephemeral port with a numpy-only predict fn, so the parent test can drive
live HTTP traffic at it and deliver SIGTERM mid-flight to assert the
graceful-drain contract (admissions stop, every accepted request answered,
exit 0, final metrics reconcile with what the parent observed).

    python serving_worker.py WORKDIR

env knobs:
    SERVE_DISPATCH_SLEEP_S  per-dispatch sleep (default 0.05) — widens the
                            drain window so SIGTERM lands with work in flight
    SERVE_MAX_BATCH         engine max_batch_size (default 4)
    SERVE_MAX_WAIT_MS       engine max_wait_ms (default 10)

Writes WORKDIR/port once the socket is bound (the parent polls for it) and
WORKDIR/metrics_final.txt (Prometheus text) during drain. Exit 0 on a clean
drain.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from paddle_tpu import serving  # noqa: E402

WORKDIR = sys.argv[1]
DISPATCH_SLEEP_S = float(os.environ.get("SERVE_DISPATCH_SLEEP_S", "0.05"))
MAX_BATCH = int(os.environ.get("SERVE_MAX_BATCH", "4"))
MAX_WAIT_MS = float(os.environ.get("SERVE_MAX_WAIT_MS", "10"))

# deterministic weights: the parent recomputes x @ W to verify responses
W = np.random.RandomState(0).randn(3, 2).astype(np.float32)


def predict(args):
    time.sleep(DISPATCH_SLEEP_S)
    return [np.asarray(args[0], np.float32) @ W]


def main():
    engine = serving.BatchingEngine(
        predict, serving.EngineConfig(
            max_batch_size=MAX_BATCH, max_wait_ms=MAX_WAIT_MS,
            max_queue_depth=256))
    server = serving.ServingServer(
        engine, port=0,
        final_metrics_path=os.path.join(WORKDIR, "metrics_final.txt"))
    # the socket is bound (and server.port real) at construction, so the
    # handshake file can be written before the serve loop starts; written
    # atomically so the parent never reads a half-written file
    tmp = os.path.join(WORKDIR, "port.tmp")
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, os.path.join(WORKDIR, "port"))
    server.serve_forever()  # installs SIGTERM/SIGINT drain handlers


if __name__ == "__main__":
    main()
