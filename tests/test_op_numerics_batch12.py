"""OpTest fixture batch 12: search/manipulation tail — searchsorted,
bucketize, index_sample, repeat_interleave, moveaxis, broadcast_to, and
the new masked_fill/take/unique_consecutive/unflatten/as_strided
(reference protocol: unittests/op_test.py:270)."""
import numpy as np
import pytest

import paddle_tpu as paddle

from op_test_base import check_grad, check_output


def test_searchsorted_and_bucketize_vs_numpy():
    edges = np.array([1.0, 3.0, 5.0, 7.0], np.float32)
    vals = np.array([[0.5, 3.0, 6.2], [7.5, 1.0, 4.9]], np.float32)
    out = paddle.searchsorted(paddle.to_tensor(edges),
                              paddle.to_tensor(vals))
    np.testing.assert_array_equal(np.asarray(out.data),
                                  np.searchsorted(edges, vals, side="left"))
    out_r = paddle.searchsorted(paddle.to_tensor(edges),
                                paddle.to_tensor(vals), right=True)
    np.testing.assert_array_equal(
        np.asarray(out_r.data), np.searchsorted(edges, vals, side="right"))
    b = paddle.bucketize(paddle.to_tensor(vals), paddle.to_tensor(edges))
    np.testing.assert_array_equal(np.asarray(b.data),
                                  np.searchsorted(edges, vals, side="left"))


def test_index_sample_vs_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 6).astype(np.float32)
    idx = rng.randint(0, 6, (3, 4)).astype(np.int64)
    out = paddle.index_sample(paddle.to_tensor(x), paddle.to_tensor(idx))
    want = np.take_along_axis(x, idx, axis=1)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)


def test_repeat_interleave_and_moveaxis():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3).astype(np.float32)
    out = paddle.repeat_interleave(paddle.to_tensor(x), 2, axis=1)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.repeat(x, 2, axis=1), rtol=1e-6)
    y = rng.randn(2, 3, 4).astype(np.float32)
    out2 = paddle.moveaxis(paddle.to_tensor(y), [0, 2], [2, 0])
    np.testing.assert_allclose(np.asarray(out2.data),
                               np.moveaxis(y, [0, 2], [2, 0]), rtol=1e-6)


def test_broadcast_to_and_grad():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 3).astype(np.float32)
    check_output(lambda t: paddle.broadcast_to(t, [4, 3]),
                 lambda a: np.broadcast_to(a, (4, 3)).copy(), [x])
    check_grad(lambda t: paddle.broadcast_to(t, [4, 3]), [x])


# ---- new ops ----

def test_masked_fill():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 4).astype(np.float32)
    m = x > 0.5
    out = paddle.masked_fill(paddle.to_tensor(x), paddle.to_tensor(m), -9.0)
    want = np.where(m, -9.0, x)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)
    # broadcast mask over rows
    m1 = np.array([True, False, True, False])
    out1 = paddle.masked_fill(paddle.to_tensor(x), paddle.to_tensor(m1), 0.0)
    np.testing.assert_allclose(np.asarray(out1.data),
                               np.where(m1[None, :], 0.0, x), rtol=1e-6)


@pytest.mark.parametrize("mode,np_mode", [("wrap", "wrap"),
                                          ("clip", "clip")])
def test_take_modes_vs_numpy(mode, np_mode):
    rng = np.random.RandomState(4)
    x = rng.randn(3, 4).astype(np.float32)
    idx = np.array([[0, 13, -1], [25, -30, 5]], np.int64)
    out = paddle.take(paddle.to_tensor(x), paddle.to_tensor(idx), mode=mode)
    want = np.take(x.reshape(-1), idx, mode=np_mode)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)


def test_take_in_range_and_bad_mode():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    idx = np.array([0, 5, 2], np.int64)
    out = paddle.take(paddle.to_tensor(x), paddle.to_tensor(idx))
    np.testing.assert_allclose(np.asarray(out.data), [0.0, 5.0, 2.0])
    with pytest.raises(ValueError):
        paddle.take(paddle.to_tensor(x), paddle.to_tensor(idx), mode="nope")


def test_unique_consecutive_flat_and_axis():
    x = np.array([1, 1, 2, 2, 2, 3, 1, 1], np.int64)
    out, inv, cnt = paddle.unique_consecutive(
        paddle.to_tensor(x), return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(np.asarray(out.data), [1, 2, 3, 1])
    np.testing.assert_array_equal(np.asarray(inv.data),
                                  [0, 0, 1, 1, 1, 2, 3, 3])
    np.testing.assert_array_equal(np.asarray(cnt.data), [2, 3, 1, 2])
    m = np.array([[1, 2], [1, 2], [3, 4]], np.float32)
    out2 = paddle.unique_consecutive(paddle.to_tensor(m), axis=0)
    np.testing.assert_allclose(np.asarray(out2.data),
                               [[1, 2], [3, 4]], rtol=1e-6)


def test_unflatten_infer_and_roundtrip():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 12, 3).astype(np.float32)
    out = paddle.unflatten(paddle.to_tensor(x), 1, [3, -1])
    assert np.asarray(out.data).shape == (2, 3, 4, 3)
    np.testing.assert_allclose(np.asarray(out.data),
                               x.reshape(2, 3, 4, 3), rtol=1e-6)
    out2 = paddle.unflatten(paddle.to_tensor(x), -1, [3, 1])
    assert np.asarray(out2.data).shape == (2, 12, 3, 1)


def test_as_strided_matches_numpy_view():
    x = np.arange(12, dtype=np.float32)
    out = paddle.as_strided(paddle.to_tensor(x), [3, 2], [4, 1], offset=1)
    want = np.lib.stride_tricks.as_strided(
        x[1:], shape=(3, 2), strides=(16, 4)).copy()
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)
    # overlapping-window trick: sliding windows of size 3
    win = paddle.as_strided(paddle.to_tensor(x), [10, 3], [1, 1])
    np.testing.assert_allclose(
        np.asarray(win.data),
        np.lib.stride_tricks.sliding_window_view(x, 3)[:10], rtol=1e-6)


def test_unique_consecutive_empty_and_dtype():
    out = paddle.unique_consecutive(
        paddle.to_tensor(np.array([], np.float32)))
    assert np.asarray(out.data).shape == (0,)
    _, inv = paddle.unique_consecutive(
        paddle.to_tensor(np.array([1, 1, 2], np.int64)),
        return_inverse=True, dtype="int32")
    assert np.asarray(inv.data).dtype == np.int32


def test_as_strided_rejects_bad_args():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32))
    with pytest.raises(ValueError):
        paddle.as_strided(x, [2, 3], [4])  # length mismatch
    with pytest.raises(ValueError):
        paddle.as_strided(x, [5], [3])  # index 12 overruns the buffer


def test_unflatten_rejects_bad_shape():
    x = paddle.to_tensor(np.zeros((2, 12), np.float32))
    with pytest.raises(ValueError):
        paddle.unflatten(x, 1, [-1, -1])
    with pytest.raises(ValueError):
        paddle.unflatten(x, 1, [5, -1])
