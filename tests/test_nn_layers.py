"""nn layer tests (dygraph/static parity analog of the reference's
unittests/test_layers.py style)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_shapes_and_grad():
    layer = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    out = layer(x)
    assert out.shape == [2, 4]
    out.sum().backward()
    assert layer.weight.grad is not None
    assert layer.weight.grad.shape == [8, 4]
    assert layer.bias.grad.shape == [4]


def test_conv2d_matches_expected_shape():
    conv = nn.Conv2D(3, 16, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    assert conv(x).shape == [2, 16, 8, 8]


def test_conv2d_numerics_vs_numpy():
    # 1x1 conv == per-pixel matmul
    conv = nn.Conv2D(2, 3, 1, bias_attr=False)
    x = paddle.randn([1, 2, 4, 4])
    out = conv(x).numpy()
    w = conv.weight.numpy().reshape(3, 2)
    ref = np.einsum("oc,nchw->nohw", w, x.numpy())
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5])
    bn.train()
    out = bn(x)
    m = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(4), atol=1e-5)
    # running stats moved from init
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(16)
    x = paddle.randn([4, 16])
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.std(-1), np.ones(4), atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_dropout_modes():
    drop = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    drop.eval()
    np.testing.assert_allclose(drop(x).numpy(), x.numpy())
    drop.train()
    out = drop(x).numpy()
    assert (out == 0).mean() > 0.3
    kept = out[out != 0]
    np.testing.assert_allclose(kept, np.full_like(kept, 2.0))


def test_sequential_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = model.state_dict()
    assert "0.weight" in sd and "2.bias" in sd
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.set_state_dict(sd)
    np.testing.assert_allclose(model2[0].weight.numpy(),
                               model[0].weight.numpy())


def test_loss_cross_entropy_vs_numpy():
    logits_np = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    labels_np = np.array([0, 2, 1, 4])
    loss = nn.CrossEntropyLoss()(paddle.to_tensor(logits_np),
                                 paddle.to_tensor(labels_np))
    # numpy reference
    e = np.exp(logits_np - logits_np.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels_np]).mean()
    np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-5)


def test_mse_and_l1():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 1.0])
    np.testing.assert_allclose(nn.MSELoss()(a, b).numpy(), (4 + 1) / 2)
    np.testing.assert_allclose(nn.L1Loss()(a, b).numpy(), (2 + 1) / 2)


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 5, 16])
    out = mha(x, x, x)
    assert out.shape == [2, 5, 16]
    out.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    out = enc(x)
    assert out.shape == [2, 6, 16]


def test_lstm_forward_backward():
    lstm = nn.LSTM(input_size=8, hidden_size=16, num_layers=2)
    x = paddle.randn([4, 10, 8])
    out, (h, c) = lstm(x)
    assert out.shape == [4, 10, 16]
    assert h.shape == [2, 4, 16]
    out.sum().backward()
    assert lstm.weight_ih_l0.grad is not None


def test_gru_bidirectional():
    gru = nn.GRU(input_size=8, hidden_size=16, direction="bidirect")
    x = paddle.randn([2, 5, 8])
    out, h = gru(x)
    assert out.shape == [2, 5, 32]


def test_activations():
    x = paddle.to_tensor([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
    np.testing.assert_allclose(
        nn.functional.softmax(x).numpy().sum(), 1.0, rtol=1e-6)
    g = nn.GELU()(x).numpy()
    assert g[0] < 0 and abs(g[1]) < 1e-6 and g[2] > 1.9


def test_parameters_traversal():
    model = nn.Sequential(nn.Linear(4, 4), nn.Sequential(nn.Linear(4, 4)))
    names = [n for n, _ in model.named_parameters()]
    assert "0.weight" in names and "1.0.weight" in names
    assert len(model.parameters()) == 4
